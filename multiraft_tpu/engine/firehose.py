"""Columnar firehose frames — the high-throughput serving wire format.

The round-4 breakdown (BENCHMARKS.md "serving") proved the framed
path's binding constraint was not decode or dispatch but the PER-OP
Python object path: one dataclass + one submit + one ticket + one
apply dispatch per op caps the in-process ceiling at ~45k ops/s on
this host.  The firehose removes the per-op path entirely:

* ONE ``bytes`` blob per frame, columnar (struct-of-arrays): opcode /
  group / client / command-id / length columns as packed little-endian
  numpy arrays, key/value bytes concatenated.  Encode and decode are a
  handful of vectorized array ops + one string-materialization pass —
  no per-op codec objects on either side.
* The engine binds a frame's rows to log slots as contiguous RUNS
  (engine/host.py ``start_run``): one payload entry per (group, accept
  batch), not per op.
* Apply happens per committed SLICE (engine/kv.py
  ``BatchedKV._apply_slice``): the dict mutations remain per-row (the
  state machine is the state machine) but every cost around them —
  binding, frontier bookkeeping, ticket resolution, reply assembly —
  is per-slice or per-frame.
* Failures (leader-change truncation) surface as per-ROW error codes
  in the reply; the CLIENT retries failed rows under the same
  (client_id, command_id) — session dedup makes the retry
  exactly-once.  This moves retry off the server's hot loop (the
  per-op ``batch`` path keeps its server-side resubmit semantics).

Layout (little-endian)::

    request:  u32 n | u8 op[n] | u32 group[n] | u64 client[n]
              | u64 command[n] | u16 key_len[n] | u32 val_len[n]
              | key bytes (concat) | value bytes (concat)
    reply:    u32 n | u8 err[n] | u32 val_len[n] | value bytes

Err codes: 0 = OK, 1 = RETRY (binding lost to a leader change —
resubmit), 2 = TIMEOUT (frame deadline expired before resolve).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "FH_OK",
    "FH_RETRY",
    "FH_TIMEOUT",
    "FH_WRONG_GROUP",
    "FH_NO_KEY",
    "MAX_FIREHOSE_ROWS",
    "pack_request",
    "unpack_request",
    "pack_reply",
    "unpack_reply",
    "FirehoseFrame",
]

FH_OK = 0
FH_RETRY = 1
FH_TIMEOUT = 2
# Sharded service only: the row's shard is not served by the addressed
# replica group under the config its apply saw — the client re-queries
# the config and re-routes (reference semantics: shardkv ErrWrongGroup,
# shardkv/common.go:12-18).
FH_WRONG_GROUP = 3
# Sharded Get of an absent key (reference: ErrNoKey) — distinct from
# the plain-KV convention of empty-string reads.
FH_NO_KEY = 4

# Largest row count one firehose frame may carry — the ONE limit both
# the server (EngineKVService.MAX_FIREHOSE) and the clerks
# (FirehoseClerk.MAX_FRAME) enforce; a clerk-side split bound above
# the server's cap would make every oversized batch permanently
# rejected.
MAX_FIREHOSE_ROWS = 65536

_U32 = np.dtype("<u4")
_U64 = np.dtype("<u8")
_U16 = np.dtype("<u2")


def pack_request(
    ops: np.ndarray,
    groups: np.ndarray,
    clients: np.ndarray,
    commands: np.ndarray,
    keys: Sequence[bytes],
    values: Sequence[bytes],
) -> bytes:
    """Pack columns into one request blob.  ``keys``/``values`` are
    per-row byte strings (empty for ops without one)."""
    n = len(ops)
    if n > MAX_FIREHOSE_ROWS:
        # The row count travels as u32, but the server rejects frames
        # above MAX_FIREHOSE_ROWS anyway — fail on the clerk side
        # before paying the pack + network round trip.
        raise ValueError(
            f"firehose frame has {n} rows; the server caps frames at "
            f"{MAX_FIREHOSE_ROWS}"
        )
    for r, k in enumerate(keys):
        if len(k) >= 2 ** 16:
            # The wire key-length column is u16; packing a longer key
            # would silently truncate the length and desync every
            # later row's key offset.
            raise ValueError(
                f"firehose key at row {r} is {len(k)} bytes; the wire "
                f"format caps keys below {2 ** 16} bytes"
            )
        if len(values[r]) >= 2 ** 32:
            # Value lengths are u32: a longer value wraps the length
            # column and desyncs every later row's value offset.
            raise ValueError(
                f"firehose value at row {r} is {len(values[r])} bytes; "
                f"the wire format caps values below {2 ** 32} bytes"
            )
    key_blob = b"".join(keys)
    val_blob = b"".join(values)
    parts = [
        np.uint32(n).tobytes(),
        np.asarray(ops, np.uint8).tobytes(),
        np.asarray(groups, _U32).tobytes(),
        np.asarray(clients, _U64).tobytes(),
        np.asarray(commands, _U64).tobytes(),
        np.asarray([len(k) for k in keys], _U16).tobytes(),
        np.asarray([len(v) for v in values], _U32).tobytes(),
        key_blob,
        val_blob,
    ]
    return b"".join(parts)


def unpack_request(
    blob: bytes,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, List[str], List[str]]:
    """Decode a request blob into columns + materialized key/value
    strings (one pass; the only per-row Python work on the hot path)."""
    n = int(np.frombuffer(blob, _U32, 1, 0)[0])
    off = 4
    ops = np.frombuffer(blob, np.uint8, n, off); off += n
    groups = np.frombuffer(blob, _U32, n, off); off += 4 * n
    clients = np.frombuffer(blob, _U64, n, off); off += 8 * n
    commands = np.frombuffer(blob, _U64, n, off); off += 8 * n
    key_len = np.frombuffer(blob, _U16, n, off); off += 2 * n
    val_len = np.frombuffer(blob, _U32, n, off); off += 4 * n
    keys: List[str] = []
    vals: List[str] = []
    mv = memoryview(blob)
    ko = off
    for ln in key_len.tolist():
        keys.append(str(mv[ko: ko + ln], "utf-8"))
        ko += ln
    vo = ko
    for ln in val_len.tolist():
        vals.append(str(mv[vo: vo + ln], "utf-8"))
        vo += ln
    if vo != len(blob):
        raise ValueError("malformed firehose frame: length mismatch")
    return ops, groups, clients, commands, keys, vals


def pack_reply(err: np.ndarray, values: Sequence[bytes]) -> bytes:
    n = len(err)
    if n > MAX_FIREHOSE_ROWS:
        # Replies mirror request frames row-for-row, so a validated
        # request can never get here; guard anyway — the u32 row count
        # would wrap silently.
        raise ValueError(
            f"firehose reply has {n} rows; frames cap at "
            f"{MAX_FIREHOSE_ROWS}"
        )
    for r, v in enumerate(values):
        if len(v) >= 2 ** 32:
            # u32 value-length column: a longer value wraps the length
            # and desyncs every later row's value offset.
            raise ValueError(
                f"firehose reply value at row {r} is {len(v)} bytes; "
                f"the wire format caps values below {2 ** 32} bytes"
            )
    return b"".join([
        np.uint32(n).tobytes(),
        np.asarray(err, np.uint8).tobytes(),
        np.asarray([len(v) for v in values], _U32).tobytes(),
        b"".join(values),
    ])


def unpack_reply(blob: bytes) -> Tuple[np.ndarray, List[str]]:
    n = int(np.frombuffer(blob, _U32, 1, 0)[0])
    off = 4
    err = np.frombuffer(blob, np.uint8, n, off); off += n
    val_len = np.frombuffer(blob, _U32, n, off); off += 4 * n
    vals: List[str] = []
    mv = memoryview(blob)
    for ln in val_len.tolist():
        vals.append(str(mv[off: off + ln], "utf-8"))
        off += ln
    return err, vals


class FirehoseFrame:
    """Server-side state of one in-flight firehose frame.

    Holds the decoded columns, the per-row outcome array, and the
    count of unresolved WRITE rows; the engine's slice apply/evict
    paths mutate rows in bulk through :meth:`rows_applied` /
    :meth:`rows_failed`.  Gets are answered at completion time (after
    the frame's writes resolve), mirroring the framed batch path's
    read-after-own-writes ordering."""

    __slots__ = (
        "ops", "groups", "clients", "commands", "keys", "vals",
        "ops_l", "clients_l", "commands_l",
        "err", "pending_writes", "submit_tick", "write_rows",
    )

    def __init__(self, blob: bytes, submit_tick: int) -> None:
        (self.ops, self.groups, self.clients, self.commands,
         self.keys, self.vals) = unpack_request(blob)
        n = len(self.ops)
        # List mirrors for the apply loop: per-row list indexing is
        # ~3x cheaper than per-row ndarray indexing, and .tolist() is
        # one C pass per frame.
        self.ops_l = self.ops.tolist()
        self.clients_l = self.clients.tolist()
        self.commands_l = self.commands.tolist()
        self.err = np.full(n, FH_TIMEOUT, np.uint8)
        self.write_rows = np.nonzero(self.ops != 0)[0]
        # Gets resolve at completion; only writes ride the log.
        self.err[self.ops == 0] = FH_OK
        self.pending_writes = int(len(self.write_rows))
        self.submit_tick = submit_tick

    def __len__(self) -> int:
        return len(self.ops)

    @property
    def done(self) -> bool:
        return self.pending_writes == 0

    def rows_applied(self, rows: np.ndarray) -> None:
        """``rows`` are ORIGINAL frame row indices (a slice of the
        group-sorted order array a run carries)."""
        self.err[rows] = FH_OK
        self.pending_writes -= len(rows)

    def rows_failed(self, rows: np.ndarray) -> None:
        self.err[rows] = FH_RETRY
        self.pending_writes -= len(rows)

    def rows_done(self, rows: np.ndarray, errs: np.ndarray) -> None:
        """Resolve rows with MIXED outcomes (the sharded apply path:
        some rows OK, some ErrWrongGroup under the config their apply
        saw)."""
        self.err[rows] = errs
        self.pending_writes -= len(rows)
