"""KV service on the batched engine — thousands of replicated KV state
machines advanced by the device tick loop.

This is the service layer's "tpu backend" (SURVEY §7.1's
ConsensusEngine interface; BASELINE configs 4/5): the engine consensus-
orders (term, index) pairs on device; command payloads stay host-side
keyed ``(group, index)``; this module applies the committed frontier to
per-group KV maps, resolves submission tickets, and records porcupine
operations (in tick time) so linearizability is verifiable on sampled
groups exactly as the north star demands.

Client-visible semantics match kvraft's apply path
(reference: kvraft/server.go:98-128): Get reads the applied state at
its log position; Put/Append are exactly-once per (group, index).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from ..porcupine.kv import OP_GET, OP_PUT, KvInput, KvOutput
from ..porcupine.model import Operation
from .firehose import FirehoseFrame
from .frontier import FrontierService
from .host import EngineDriver, PayloadSlice

__all__ = ["KVOp", "Ticket", "BatchedKV", "apply_kv_op"]


@dataclasses.dataclass
class KVOp:
    op: int = OP_GET  # porcupine op codes
    key: str = ""
    value: str = ""
    # Session dedup (kvraft semantics, reference: kvraft/server.go
    # lastApplied map): command_id > 0 makes a Put/Append exactly-once
    # per client even when the caller resubmits after a lost leader —
    # required by any at-least-once transport (the TCP serving path).
    # 0 = no dedup (trusted single-submit callers, e.g. the bench
    # firehose and in-process tests).
    client_id: int = 0
    command_id: int = 0


def apply_kv_op(kv: Dict[str, str], sessions: Dict[int, int], op: KVOp):
    """The kvraft apply semantics (dup-check + mutate + session
    update) as one shared function — the live apply path and the
    split-persistence recovery replay both use it, so the two can
    never drift (reference: kvraft/server.go:98-128).  Returns
    ``(output, dup)``."""
    dup = (
        op.op != OP_GET
        and op.command_id > 0
        and sessions.get(op.client_id, 0) >= op.command_id
    )
    if op.op == OP_GET:
        out = kv.get(op.key, "")
    elif dup:
        out = ""  # duplicate write: resolve, skip the apply
    elif op.op == OP_PUT:
        kv[op.key] = op.value
        out = ""
    else:
        kv[op.key] = kv.get(op.key, "") + op.value
        out = ""
    if op.op != OP_GET and op.command_id > 0 and not dup:
        sessions[op.client_id] = op.command_id
    return out, dup


@dataclasses.dataclass
class Ticket:
    group: int
    done: bool = False
    failed: bool = False  # lost to a leader change; caller resubmits
    value: str = ""
    index: int = -1
    submit_tick: int = 0
    done_tick: int = 0


class BatchedKV(FrontierService):
    """Many independent KV groups on one :class:`EngineDriver`."""

    def __init__(
        self,
        driver: EngineDriver,
        record_groups: Optional[List[int]] = None,
    ) -> None:
        super().__init__(driver)
        G = driver.cfg.G
        self.data: List[Dict[str, str]] = [dict() for _ in range(G)]
        # Per-group client sessions: client_id -> (last command_id).
        # Writes at or below it are duplicates and must not re-apply.
        self.sessions: List[Dict[int, int]] = [dict() for _ in range(G)]
        self._record = set(record_groups or [])
        self.histories: Dict[int, List[Operation]] = {
            g: [] for g in self._record
        }
        self._next_client = 0
        # Durability hook (distributed/engine_server.py): fired for
        # every NON-DUPLICATE applied write, in apply (= commit) order
        # — the WAL must be a commit-ordered redo log or replay can
        # disagree with reads the old incarnation acknowledged.
        self.on_write = None  # (group, KVOp)
        # Optional route validator (key: str, G: int) -> group.  When
        # set (the plain-KV server installs route_group), submit_frame
        # rejects frames whose group column disagrees with the
        # canonical hash — a misrouted write would land in the wrong
        # group's sessions and break dedup silently.  Left None for
        # the sharded service, whose group column carries
        # config-assigned gids re-checked at apply time instead.
        self.route_check = None

    # -- submission (DeferredConsensus.submit) ---------------------------

    def submit(self, group: int, op: KVOp) -> Ticket:
        t = Ticket(group=group, submit_tick=self._now())
        self.driver.start(group, (op, t))
        return t

    def get(self, group: int, key: str) -> Ticket:
        """Linearizable read served WITHOUT a log entry — the batched
        form of the ReadIndex optimization the reference never built
        (SURVEY §3.4: "Gets go through the log too ... no
        lease/read-index optimization anywhere").

        Classic ReadIndex records the leader's commit index and
        confirms leadership with a quorum round before serving.  Here
        both steps collapse: this service is the *sole acker* of every
        write in the group (acks happen only at :meth:`pump`'s applied
        frontier), so ``applied_upto[g]`` already covers every
        acknowledged write — the read index is satisfied by
        construction, and no concurrent acker exists for a stale leader
        to race.  The read linearizes at its submit tick.  Reads
        therefore cost zero device work; Gets submitted via
        :meth:`submit` still take the log path (useful for the
        cross-host runtime, where per-replica ackers make the quorum
        round real again).
        """
        now = self._now()
        out = self.data[group].get(key, "")
        t = Ticket(
            group=group, done=True, value=out,
            submit_tick=now, done_tick=now,
        )
        self._record_op(group, KvInput(op=OP_GET, key=key), out, now, now)
        return t

    def _record_op(
        self, g: int, inp: KvInput, out: str, call: int, ret: int
    ) -> None:
        """Append a porcupine operation for a recorded group.  ``ret``
        is padded by 0.5 so intervals are non-degenerate in tick time."""
        if g in self._record:
            # Porcupine history capture: only for groups the TEST
            # harness opted into recording; production serves with
            # _record empty.
            self.histories[g].append(  # graftlint: disable=unbounded-queue
                Operation(
                    client_id=0,
                    input=inp,
                    call=float(call),
                    output=KvOutput(value=out),
                    ret=float(ret) + 0.5,
                )
            )

    def _now(self) -> int:
        # Host-side tick mirror: avoids a device readback per submit.
        return self.driver.tick

    def _on_evicted(self, payload: Any) -> None:
        """A (group, index) binding was overwritten: the old command lost
        its log slot to a leader change and will never commit there —
        fail its ticket so the caller can resubmit (the batched analog of
        kvraft's ErrWrongLeader wait-channel resolution,
        reference: kvraft/server.go:98-128).  Firehose slices fail all
        their rows at once — the CLIENT resubmits those (row-level
        RETRY errs in the reply; dedup keeps the retry exactly-once)."""
        if isinstance(payload, PayloadSlice):
            payload.frame.rows_failed(payload.rows)
            return
        _, ticket = payload
        if ticket is not None and not ticket.done:
            ticket.done = True
            ticket.failed = True

    # -- columnar firehose (engine/firehose.py) --------------------------

    def submit_frame(self, blob: bytes) -> FirehoseFrame:
        """Enqueue one columnar frame: write rows are grouped into
        contiguous per-group RUNS (one pending entry + one backlog bump
        per run — no per-op Python on the submit path).  Stable sort
        preserves each client's submission order within a group, which
        session dedup requires.  Gets do not ride the log; they answer
        at frame completion (read-after-own-frame-writes, like the
        framed batch path)."""
        f = FirehoseFrame(blob, self._now())
        if len(f.groups) and int(f.groups.max()) >= self.driver.cfg.G:
            raise ValueError(
                f"frame routes to group {int(f.groups.max())} >= G="
                f"{self.driver.cfg.G}"
            )
        if self.route_check is not None:
            G = self.driver.cfg.G
            for r, key in enumerate(f.keys):
                want = self.route_check(key, G)
                if int(f.groups[r]) != want:
                    raise ValueError(
                        f"frame row {r} key {key!r} routed to group "
                        f"{int(f.groups[r])}, expected {want}"
                    )
        wr = f.write_rows
        if len(wr):
            g = f.groups[wr]
            order = np.argsort(g, kind="stable")
            rows_sorted = wr[order]
            gs = g[order]
            bounds = np.nonzero(np.diff(gs))[0] + 1
            starts = np.concatenate([[0], bounds])
            ends = np.concatenate([bounds, [len(gs)]])
            for s, e in zip(starts.tolist(), ends.tolist()):
                self.driver.start_run(int(gs[s]), f, rows_sorted[s:e])
        return f

    def _apply_slice(self, g: int, idx: int, sl: PayloadSlice, now: int) -> None:
        """Bulk apply of one committed firehose slice: the per-row work
        is exactly the state machine (dup check + dict mutate + session
        update — apply_kv_op semantics, reference: kvraft/server.go:
        98-128); everything around it resolved per-slice."""
        f = sl.frame
        data = self.data[g]
        sess = self.sessions[g]
        ops_l = f.ops_l
        clients_l = f.clients_l
        commands_l = f.commands_l
        keys = f.keys
        vals = f.vals
        record = g in self._record
        on_write = self.on_write
        for r in sl.rows.tolist():
            cid = clients_l[r]
            cmd = commands_l[r]
            if cmd > 0 and sess.get(cid, 0) >= cmd:
                continue  # duplicate write: already applied
            k = keys[r]
            if ops_l[r] == OP_PUT:
                data[k] = vals[r]
            else:
                data[k] = data.get(k, "") + vals[r]
            if cmd > 0:
                sess[cid] = cmd
            if on_write is not None:
                on_write(g, KVOp(op=ops_l[r], key=k, value=vals[r],
                                 client_id=cid, command_id=cmd))
            if record:
                self._record_op(
                    g, KvInput(op=ops_l[r], key=k, value=vals[r]),
                    "", f.submit_tick, now,
                )
        f.rows_applied(sl.rows)

    # -- pumping/sweeping inherited from FrontierService -----------------

    def _apply(self, g: int, idx: int, payload: Any, now: int) -> None:
        if payload is None:
            return  # command lost to a leader change before binding
        op, ticket = payload
        out, dup = apply_kv_op(self.data[g], self.sessions[g], op)
        if op.op != OP_GET and op.command_id > 0 and not dup:
            if self.on_write is not None:
                self.on_write(g, op)
        if ticket is not None and not ticket.done:
            ticket.done = True
            ticket.value = out
            ticket.index = idx
            ticket.done_tick = now
            # Tickets resolve at the apply readback.  A dup-suppressed
            # write is NOT recorded: the logical op was already recorded
            # when its first incarnation applied, and a second Operation
            # for one state change would make porcupine reject a correct
            # history (resubmit-under-same-command_id path).
            if not dup:
                self._record_op(
                    g,
                    KvInput(op=op.op, key=op.key, value=op.value),
                    out,
                    ticket.submit_tick,
                    now,
                )

    # -- checkpoint -------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        blob = super().state_dict()
        blob["data"] = [dict(m) for m in self.data]
        blob["sessions"] = [dict(m) for m in self.sessions]
        blob["histories"] = {g: list(h) for g, h in self.histories.items()}
        return blob

    def load_state_dict(self, blob: Dict[str, Any]) -> None:
        super().load_state_dict(blob)
        self.data = [dict(m) for m in blob["data"]]
        self.sessions = [dict(m) for m in blob.get("sessions", [])] or [
            dict() for _ in self.data
        ]
        self.histories = {g: list(h) for g, h in blob["histories"].items()}
        self._record = set(self.histories.keys())

    # -- verification ----------------------------------------------------

    def check_sampled_linearizability(self, timeout: float = 5.0):
        """Porcupine over the recorded groups' histories — the sampled-
        shard verification of the north star."""
        from ..porcupine.kv import kv_model
        from ..porcupine.visualization import assert_linearizable

        for g, hist in self.histories.items():
            assert_linearizable(
                kv_model, hist, timeout=timeout, name=f"engine-group-{g}"
            )
        return True
