"""Fused multi-tick device pipeline for the serving path.

The serial :meth:`EngineDriver.step` loop pays two host round-trips per
tick whenever commands are in flight: the ``np.minimum`` backlog clip
that builds ``new_cmds`` (host → device), and the accepted/starts/terms
readback that binds payloads (device → host).  At serving shapes the
readback dominates the pump — LOADCURVE_r03 measured ``host.step`` at
538 µs/op against 29 µs/op for ingress decode.

:func:`step_ticks` removes both: one ``lax.scan`` advances
``ticks_per_pump`` ticks entirely on device, carrying the backlog
decrement in the scan carry (``new_cmds`` is recomputed per tick from
the carried backlog, so accepted commands are never re-ingested), and
stacking the per-tick metrics so the host syncs ONCE per pump and
replays the payload binding from the stacked record.  The fault model
rides inside the scan: per-tick drop masks (same ``fold_in(tick_key,
0xFA)`` stream as the serial loop) and the partition edge mask, so a
chaos run fuses identically to a clean one.  Host-side reorder
(`_apply_reorder`) is inherently unfusable — drivers with reordering
in flight fall back to the serial loop (see
``EngineDriver.fused_eligible``).

Bit-parity with the serial loop is a hard contract
(tests/test_engine_pipeline.py pins it via the state_planes content
fingerprints): same keys (``fold_in(key, tick0 + 1 + i)`` reproduces
the serial per-tick fold), same ingest clip, same decrement order.

:class:`PendingTicks` is the dispatch/complete split on top of it: the
scheduler loop dispatches a batch without waiting (JAX async dispatch
makes the returned arrays futures), a dedicated pump thread blocks in
:meth:`PendingTicks.fetch`, and the loop folds the fetched record back
in :meth:`EngineDriver.complete_ticks` — so socket I/O, decode and
acks proceed during device compute (distributed/engine_pump.py).
"""

from __future__ import annotations

import functools
import time
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .core import EngineConfig, EngineState, Mailbox, tick_impl
from .host import apply_faults, mask_active

__all__ = ["step_ticks", "PendingTicks"]


@functools.partial(
    jax.jit, static_argnums=(0, 3, 4, 5), donate_argnums=(1, 2)
)
def step_ticks(
    cfg: EngineConfig,
    state: EngineState,
    inbox: Mailbox,
    n_ticks: int,
    with_drop: bool,
    with_edges: bool,
    backlog: jnp.ndarray,  # i32[G]: host backlog (clipped), scan carry
    drop_prob: jnp.ndarray,  # f32 scalar (unused when not with_drop)
    edge_mask: jnp.ndarray,  # bool[G,P,P]; dummy when not with_edges
    tick0: jnp.ndarray,  # i32 scalar: host tick BEFORE this batch
    key: jax.Array,
):
    """``n_ticks`` consensus rounds fused under one scan, with the
    backlog/new_cmds computation in the carry and every per-tick metric
    stacked (``rec[k]`` has a leading ``[n_ticks]`` axis).

    Returns ``(state, inbox, backlog_left, rec)``.  ``with_drop`` /
    ``with_edges`` are static so the clean path compiles none of the
    fault machinery; ``tick0`` and ``backlog`` are device values so a
    moving tick counter never retraces."""

    def body(carry, i):
        st, mb, bl = carry
        # Parity with the serial loop: it increments the host tick
        # FIRST, then folds — tick i of this batch is tick0 + 1 + i.
        tick_key = jax.random.fold_in(key, tick0 + 1 + i)
        new_cmds = jnp.minimum(bl, jnp.int32(cfg.INGEST))
        st, mb, m = tick_impl(cfg, st, mb, new_cmds, tick_key)
        if with_drop:
            mb = apply_faults(
                mb, jax.random.fold_in(tick_key, 0xFA), drop_prob, cfg
            )
        if with_edges:
            mb = mask_active(mb, lambda _, a: a & edge_mask)
        bl = bl - m["accepted"]
        return (st, mb, bl), m

    (state, inbox, backlog), rec = jax.lax.scan(
        body, (state, inbox, backlog), jnp.arange(n_ticks, dtype=jnp.int32)
    )
    return state, inbox, backlog, rec


class PendingTicks:
    """A dispatched, not-yet-completed fused tick batch.

    Created by :meth:`EngineDriver.dispatch_ticks` (scheduler loop,
    non-blocking); :meth:`fetch` blocks until the stacked metrics are
    on host and is the ONE call safe to run off the loop thread (the
    engine-pump thread's whole job); the result then goes back to the
    loop for :meth:`EngineDriver.complete_ticks`.

    ``accepts_dev`` stays on device: later dispatches subtract it from
    the host backlog so an in-flight batch's accepted commands are
    never re-ingested (the pipeline-depth ≥ 2 double-ingest hazard).
    """

    __slots__ = (
        "n", "tick0", "rec", "accepts_dev", "t_dispatch", "t_loop_cpu",
    )

    def __init__(
        self,
        n: int,
        tick0: int,
        rec: Dict[str, jnp.ndarray],
        accepts_dev: jnp.ndarray,
        t_dispatch: float,
    ) -> None:
        self.n = n
        self.tick0 = tick0
        self.rec = rec
        self.accepts_dev = accepts_dev
        self.t_dispatch = t_dispatch
        # Loop-side CPU the dispatch burned (the serving loop's share
        # of this pump; completion adds its own) — set by the caller.
        self.t_loop_cpu = 0.0

    def fetch(self) -> Dict[str, np.ndarray]:
        """Block until the batch's stacked metrics are host-resident.
        Pure device wait + copy: touches no driver state, so it is
        safe off the scheduler loop by construction."""
        return {k: np.asarray(v) for k, v in self.rec.items()}

    def _replace_wall(self, t: float) -> None:  # pragma: no cover - tests
        self.t_dispatch = t
