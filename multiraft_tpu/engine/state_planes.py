"""Declared state-plane classification for the engine's tensors.

The reference Go stack keeps its persistence discipline in one place
(``raft/raft.go`` persist/readPersist); the tensorized engine spreads
the same discipline over four hand-synced sites — checkpoint
save/restore (host.py, ``CKPT_VERSION``), crash-restart resets
(``restart_replica``), fresh-incarnation wipes (``reset_replica``) and
the cross-replica column clears.  This module is the single declared
source of truth those sites are checked against:

* graftlint's ``plane-class`` rule fails when an ``EngineState`` /
  ``Mailbox`` field exists without a classification here (or a stale
  entry outlives its field);
* graftlint's ``plane-lifecycle`` rule statically verifies
  ``restart_replica`` resets every VOLATILE plane, touches nothing
  PERSISTENT or CONFIG, and that ``reset_replica`` wipes everything
  except the engine-global clock and the CONFIG planes — including the
  declared :data:`CROSS_COLUMNS` ``[g, :, p]`` clears;
* ``tests/test_schema_pins.py`` pins :func:`state_fingerprint` /
  :func:`mailbox_fingerprint` against ``CKPT_VERSION`` so changing the
  plane set without a version bump fails loudly.

Plane vocabulary (raft/raft.go persist discipline, tensorized):

* ``PERSISTENT`` — survives a crash-restart (term, vote, log shape,
  snapshot floor).  ``restart_replica`` must never touch these.
* ``VOLATILE`` — knowledge rebuilt from traffic (commit/applied
  frontiers, liveness).  ``restart_replica`` must reset all of these.
* ``LEADERSHIP`` — vote tallies, replication ledgers and timers that
  are reseeded at role transitions; ``restart_replica`` MAY reset them
  (it resets the tallies and the check-quorum clock, and leaves the
  timers to the follower transition).
* ``CONFIG`` — joint-consensus membership view, managed only by the
  config-change ops (add_learner/promote/abort); neither lifecycle
  function touches it.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Tuple

__all__ = [
    "PERSISTENT",
    "VOLATILE",
    "LEADERSHIP",
    "CONFIG",
    "STATE_PLANES",
    "MAILBOX_PLANES",
    "CROSS_COLUMNS",
    "GLOBAL_FIELDS",
    "check_classification",
    "state_fingerprint",
    "mailbox_fingerprint",
    "content_fingerprint",
]

PERSISTENT = "persistent"
VOLATILE = "volatile"
LEADERSHIP = "leadership"
CONFIG = "config"

# EngineState field -> plane.  Every field of the NamedTuple in
# engine/core.py must appear exactly once (plane-class enforces it).
STATE_PLANES: Dict[str, str] = {
    # Engine-global tick clock: checkpointed, never per-replica reset.
    "tick_no": PERSISTENT,
    # raft/raft.go persist(): currentTerm, votedFor, log.
    "term": PERSISTENT,
    "voted_for": PERSISTENT,
    "base": PERSISTENT,
    "base_term": PERSISTENT,
    "log_len": PERSISTENT,
    "log_term": PERSISTENT,
    # Rebuilt from traffic after a restart.
    "role": VOLATILE,
    "commit": VOLATILE,
    "applied": VOLATILE,
    "last_heard": VOLATILE,
    "alive": VOLATILE,
    # Reseeded at role transitions (become_leader/become_candidate).
    "votes": LEADERSHIP,
    "pre_votes": LEADERSHIP,
    "last_ack": LEADERSHIP,
    "next_idx": LEADERSHIP,
    "match_idx": LEADERSHIP,
    "hb_due": LEADERSHIP,
    "elect_dl": LEADERSHIP,
    # Joint-consensus membership view (config ops only).
    "voters_old": CONFIG,
    "voters_new": CONFIG,
    "joint": CONFIG,
    "cfg_epoch": CONFIG,
    "cfg_idx": CONFIG,
}

# Mailbox fields are all in-flight message state: volatile by
# construction (restart/reset mask the edges via _mask_edges rather
# than per-field), including the config piggyback lanes — the CONFIG
# *planes* live in EngineState; the ar_cfg_* lanes merely carry them.
MAILBOX_PLANES: Dict[str, str] = {
    "vr_active": VOLATILE,
    "vr_term": VOLATILE,
    "vr_last_idx": VOLATILE,
    "vr_last_term": VOLATILE,
    "vr_pre": VOLATILE,
    "vp_active": VOLATILE,
    "vp_term": VOLATILE,
    "vp_granted": VOLATILE,
    "vp_pre": VOLATILE,
    "ar_active": VOLATILE,
    "ar_term": VOLATILE,
    "ar_prev_idx": VOLATILE,
    "ar_prev_term": VOLATILE,
    "ar_n": VOLATILE,
    "ar_terms": VOLATILE,
    "ar_commit": VOLATILE,
    "ar_snap": VOLATILE,
    "ap_active": VOLATILE,
    "ap_term": VOLATILE,
    "ap_success": VOLATILE,
    "ap_match": VOLATILE,
    "ap_conflict": VOLATILE,
    "ar_cfg_epoch": VOLATILE,
    "ar_cfg_idx": VOLATILE,
    "ar_cfg_old": VOLATILE,
    "ar_cfg_new": VOLATILE,
    "ar_cfg_joint": VOLATILE,
}

# Fields holding per-peer state ABOUT a replica in their last axis:
# reset_replica must clear the [g, :, p] column too, or a stale vote /
# match / ack of the dead incarnation leaks into the new one's ledger
# (the PR 16 regression class).
CROSS_COLUMNS: Tuple[str, ...] = (
    "votes",
    "pre_votes",
    "next_idx",
    "match_idx",
    "last_ack",
)

# Engine-global scalars with no per-replica row: exempt from the
# reset_replica must-wipe set.
GLOBAL_FIELDS: Tuple[str, ...] = ("tick_no",)


def check_classification() -> list:
    """Runtime registry-vs-NamedTuple drift problems (empty = clean).
    The static ``plane-class`` rule does the same against the AST; the
    unit test runs this against the imported classes."""
    from .core import EngineState, Mailbox

    problems = []
    for cls, planes, label in (
        (EngineState, STATE_PLANES, "STATE_PLANES"),
        (Mailbox, MAILBOX_PLANES, "MAILBOX_PLANES"),
    ):
        fields = set(cls._fields)
        declared = set(planes)
        for f in sorted(fields - declared):
            problems.append(f"{cls.__name__}.{f} unclassified in {label}")
        for f in sorted(declared - fields):
            problems.append(f"{label}[{f!r}] names no {cls.__name__} field")
        for f, plane in planes.items():
            if plane not in (PERSISTENT, VOLATILE, LEADERSHIP, CONFIG):
                problems.append(f"{label}[{f!r}] = {plane!r} is not a plane")
    for f in CROSS_COLUMNS:
        if STATE_PLANES.get(f) != LEADERSHIP:
            problems.append(
                f"CROSS_COLUMNS field {f!r} must be a LEADERSHIP plane"
            )
    return problems


def _fingerprint(fields: Tuple[str, ...], planes: Dict[str, str]) -> str:
    """Order-sensitive digest of the classified field list: checkpoint
    arrays are saved by field name but restored positionally validated,
    so both the set AND the order are schema."""
    h = hashlib.sha256()
    for f in fields:
        h.update(f"{f}={planes.get(f, '?')};".encode())
    return h.hexdigest()[:16]


def state_fingerprint() -> str:
    from .core import EngineState

    return _fingerprint(EngineState._fields, STATE_PLANES)


def mailbox_fingerprint() -> str:
    from .core import Mailbox

    return _fingerprint(Mailbox._fields, MAILBOX_PLANES)


def content_fingerprint(nt) -> str:
    """sha256 over the VALUE bytes of every field of an ``EngineState``
    or ``Mailbox`` instance, in field order (name + dtype + raw bytes
    per field).  Where :func:`state_fingerprint` pins the SCHEMA, this
    witnesses the CONTENT — the tick-parity contract's assertion that
    the fused pipeline (engine/pipeline.py) and the serial step loop
    produce bit-identical state (tests/test_engine_pipeline.py).
    Forces a device→host sync: test/diagnostic use only."""
    import numpy as np

    h = hashlib.sha256()
    for name, value in zip(type(nt)._fields, nt):
        a = np.asarray(value)
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]
