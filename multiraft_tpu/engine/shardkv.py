"""Sharded multi-group KV on the batched engine — the routing analog.

The sim backend runs the sharded stack as one process per server with
leader tickers (services/shardkv.py).  This module is the TPU-native
form (SURVEY §2.1: "shard→group table is a small device array — the
EP/expert-routing analog"): one :class:`~multiraft_tpu.engine.host.
EngineDriver` consensus-orders *every* replica group's log on device —
engine group 0 is the config RSM (the shardctrler), engine groups
``1..G-1`` are replica groups with ``gid == engine group index`` — and
a per-pump host sweep replaces the reference's three leader tickers
(config poll / shard pull / GC, reference: shardkv server tickers;
see services/shardkv.py:310-397 for the sim equivalents).

Semantics match the sim backend (and therefore the reference's shardkv
test spec, SURVEY §4.4):

* per-shard serving states SERVING / PULLING / BEPULLING / GCING;
* configs apply strictly in order, only when no migration is in flight;
* Challenge 1 — migrated shards are *deleted* at the old owner once the
  new owner has them (DeleteShard → ConfirmGC handshake through both
  groups' logs);
* Challenge 2 — unaffected shards serve during migration, and freshly
  inserted shards serve (GCING) before the old copy is deleted;
* per-shard client dedup tables migrate with the shard data.

Deliberate divergences (documented):

* The "pull shard" and "query config" RPCs become direct host reads of
  the source group's *applied* state — all groups share the host
  process, so the network hop of the sim backend is an identity; the
  read is gated on the source having applied the same config number,
  which is exactly the ErrNotReady handshake of the sim's pull RPC.
  Cross-host group placement rides the distributed transport instead
  (multiraft_tpu/distributed/), not this module.
* Proposals are deduplicated by outstanding-ticket bookkeeping rather
  than timer cadence; duplicate applies are idempotent regardless.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT, KvInput, KvOutput
from ..porcupine.model import Operation
from ..services.shardctrler import NSHARDS, Config, rebalance
from ..services.shardkv import (
    BEPULLING,
    GCING,
    PULLING,
    SERVING,
    key2shard,
)
from .firehose import FH_OK, FH_WRONG_GROUP, FirehoseFrame
from .frontier import FrontierService
from .host import EngineDriver, PayloadSlice

__all__ = [
    "ShardTicket",
    "BatchedShardKV",
    "BatchedShardClerk",
    "route_keys",
]

OK = "OK"
ERR_NO_KEY = "ErrNoKey"
ERR_WRONG_GROUP = "ErrWrongGroup"
ERR_NOT_READY = "ErrNotReady"

GET, PUT, APPEND = "Get", "Put", "Append"

_PORCUPINE_OPCODE = {GET: OP_GET, PUT: OP_PUT, APPEND: OP_APPEND}


@dataclasses.dataclass
class ShardTicket:
    """Resolution of one proposed command.  ``failed`` means the command
    lost its log slot to a leader change and never committed — the
    caller resubmits (dedup tables make write retries exactly-once)."""

    group: int
    done: bool = False
    failed: bool = False
    err: str = OK
    value: str = ""
    done_tick: int = 0
    command_id: int = 0  # set on ctrler tickets so retries can dedup


# Host payload records bound to (group, index) by the driver.  Every op
# carries a ticket slot so evictions (lost log slots) can fail it.


@dataclasses.dataclass
class _ClientOp:
    op: str
    key: str
    value: str
    client_id: int
    command_id: int
    ticket: Optional[ShardTicket] = None


@dataclasses.dataclass
class _CtrlOp:
    kind: str  # "join" | "leave" | "move"
    arg: Any
    client_id: int
    command_id: int
    ticket: Optional[ShardTicket] = None


@dataclasses.dataclass
class _ConfigOp:
    config: Config
    ticket: Optional[ShardTicket] = None


@dataclasses.dataclass
class _InsertOp:
    config_num: int
    shard: int
    data: Dict[str, str]
    latest: Dict[int, int]
    ticket: Optional[ShardTicket] = None


@dataclasses.dataclass
class _DeleteOp:
    config_num: int
    shard: int
    ticket: Optional[ShardTicket] = None


@dataclasses.dataclass
class _ConfirmOp:
    config_num: int
    shard: int
    ticket: Optional[ShardTicket] = None


@dataclasses.dataclass
class _ShardSlot:
    state: int = SERVING
    data: Dict[str, str] = dataclasses.field(default_factory=dict)
    latest: Dict[int, int] = dataclasses.field(default_factory=dict)


class _Replica:
    """Host-side applied state of one replica group (gid = engine
    group index)."""

    def __init__(self, gid: int) -> None:
        self.gid = gid
        self.cur = Config(num=0, shards=[0] * NSHARDS, groups={})
        self.prev = self.cur
        self.shards: Dict[int, _ShardSlot] = {
            s: _ShardSlot() for s in range(NSHARDS)
        }
        # Outstanding internal proposals (ticket per kind/shard).
        self.pending_config: Optional[ShardTicket] = None
        self.pending_insert: Dict[int, ShardTicket] = {}
        self.pending_delete: Dict[int, ShardTicket] = {}
        self.pending_confirm: Dict[int, ShardTicket] = {}
        # Tick when the oldest still-live proposal batch went out
        # (0 = none outstanding) — the _orchestrate stall detector.
        self.pending_since = 0
        # Group-migration seal (BatchedShardKV.export_group): a sealed
        # replica's applied state is frozen — every post-seal apply is a
        # WRONG_GROUP no-op — so the exported blob is stable across
        # export retries without draining the log.
        self.sealed = False
        # Set the moment an export blob leaves this process: from then
        # on an adopt RPC MAY have been dispatched, and unsealing would
        # risk two serving copies (unseal_group enforces this).
        self.export_dispatched = False

    def can_serve(self, shard: int) -> bool:
        """Challenge 2 gate (mirror of services/shardkv.py:225-232).
        ``getattr``: checkpoints pickled before the placement layer
        restore replicas without a ``sealed`` attribute."""
        if getattr(self, "sealed", False):
            return False
        return self.cur.shards[shard] == self.gid and self.shards[
            shard
        ].state in (SERVING, GCING)


@functools.partial(jax.jit)
def route_keys(table: jnp.ndarray, key_hashes: jnp.ndarray) -> jnp.ndarray:
    """Vectorized client-op routing: key hash → shard → engine group.

    ``table`` is the i32[NSHARDS] shard→gid array maintained by
    :meth:`BatchedShardKV.shard_table`; this is the device half of the
    reference's ``key2shard`` + config lookup
    (reference: shardkv/client.go:22-29, 68-129) for batched firehoses.
    """
    return table[key_hashes % NSHARDS]


class BatchedShardKV(FrontierService):
    """The full sharded stack on one batched engine.

    Engine group 0 = config RSM; local engine groups ``1..`` host the
    replica groups.  By default every global gid lives in this instance
    (``gid == engine group index``, the single-chip deployment).  In
    **fleet mode** — several chip-owning processes splitting one global
    gid space — pass ``gids`` (the subset hosted here, mapped onto local
    engine groups in order) and wire the two remote-migration hooks:

    * ``remote_fetch(src_gid, shard, config_num) → (data, latest) | None``
      — called each orchestration sweep while a PULLING shard's source
      gid is not local.  The hook owns the async RPC: return ``None``
      while in flight / source not caught up, and the blobs exactly
      once when ready (the sweep immediately logs the InsertOp).
    * ``remote_delete(src_gid, shard, config_num) → bool | None`` —
      Challenge-1 GC at a remote old owner.  ``None`` = in flight,
      ``True`` = deleted (confirm proceeds), ``False`` = ErrNotReady
      (re-asked next sweep).

    Config consistency across a fleet is by construction: every process
    applies the same admin ops in the same order through its own config
    RSM (``rebalance`` is deterministic), mirroring how every reference
    shardkv group converges on the same shardctrler history.
    """

    def __init__(
        self, driver: EngineDriver, gids: Optional[List[int]] = None
    ) -> None:
        if driver.cfg.G < 2:
            raise ValueError("BatchedShardKV needs G >= 2 (ctrler + >=1 group)")
        super().__init__(driver)
        G = driver.cfg.G
        if gids is None:
            self.gids = list(range(1, G))
        else:
            if len(set(gids)) != len(gids) or 0 in gids:
                raise ValueError("gids must be unique and nonzero")
            if len(gids) > G - 1:
                raise ValueError(
                    f"{len(gids)} gids need G >= {len(gids) + 1} engine groups"
                )
            self.gids = list(gids)
        # Global gid ↔ local engine group (group 0 is the config RSM).
        self._g2l = {gid: i + 1 for i, gid in enumerate(self.gids)}
        self._l2g = {i + 1: gid for i, gid in enumerate(self.gids)}
        # Config RSM applied state (group 0).
        self.configs: List[Config] = [
            Config(num=0, shards=[0] * NSHARDS, groups={})
        ]
        self._ctrl_latest: Dict[int, int] = {}
        self.reps: Dict[int, _Replica] = {g: _Replica(g) for g in self.gids}
        self._route = jnp.zeros((NSHARDS,), jnp.int32)
        self._ctrl_cmd = 0
        # Ctrler session identity for admin proposals.  Single-instance
        # deployments use 0; split-group deployments (engine/
        # split_shard.py) set a per-process id — two processes sharing
        # client 0 would collide in the ctrler dedup table and silently
        # swallow each other's joins.
        self._ctrl_client_id = 0
        self._orchestrate_enabled = True
        # Recovery gate (durable server replay): config advance keeps
        # running, but PULLS and the GC/confirm handshake must not.
        # A pull completing mid-replay would copy a slot BEFORE its
        # redo records landed, losing acked writes; the GC handshake
        # mid-replay can involve a REMOTE old owner, and during replay
        # the server's scheduler loop is blocked — the RPC could never
        # resolve, wedging recovery forever.  Replay instead re-applies
        # committed GCING→SERVING transitions from WAL "confirm"
        # records (see on_confirm / EngineShardKVService.replay_wal),
        # so config advance never needs a live handshake; a slot whose
        # confirm had NOT committed pre-crash simply stays GCING until
        # the post-replay pump loop re-runs the handshake live.
        self.migration_paused = False
        # Fleet-mode hooks (see class docstring); None = single-instance.
        self.remote_fetch = None
        self.remote_delete = None
        # Durability hooks (distributed/engine_server.py): fired at
        # apply time when a migration actually mutates shard state —
        # the WAL must cover an inserted blob before the old owner may
        # be told to GC it (the only remaining copy otherwise dies with
        # an untimely crash), and replayed deletes clear stale
        # BEPULLING slots that would wedge config advance after a
        # restore from an older checkpoint.
        self.on_insert = None  # (gid, shard, config_num, data, latest)
        self.on_delete = None  # (gid, shard, config_num)
        # Fired when a committed confirm actually flips GCING→SERVING.
        # The WAL record lets recovery re-apply the transition locally
        # instead of re-running the (possibly cross-process) GC
        # handshake — the handshake's peer may be unreachable while the
        # restarting server's loop is blocked in replay.
        self.on_confirm = None  # (gid, shard, config_num)
        # Fired in apply (= commit) order — the durable WAL must be a
        # commit-ordered redo log, not submit-ordered (evict-and-
        # resubmit can commit in a different order than submission).
        self.on_write = None   # (gid, _ClientOp), non-duplicate applies
        self.on_ctrl = None    # (_CtrlOp), non-duplicate config applies

    # -- checkpoint (pairs with EngineDriver.save/restore) ----------------

    def state_dict(self) -> Dict[str, Any]:
        import copy

        blob = super().state_dict()
        # Deep-copy: the checkpoint must not alias live host state
        # (tickets inside reps resolve after the snapshot is taken).
        blob["configs"] = copy.deepcopy(self.configs)
        blob["ctrl_latest"] = dict(self._ctrl_latest)
        blob["reps"] = copy.deepcopy(self.reps)
        blob["route"] = np.asarray(self._route)
        blob["ctrl_cmd"] = self._ctrl_cmd
        blob["orchestrate"] = self._orchestrate_enabled
        blob["gids"] = list(self.gids)
        # After adopt/drop the gid→slot mapping is no longer the
        # constructor's enumeration order — it must travel too.
        blob["g2l"] = dict(self._g2l)
        return blob

    def load_state_dict(self, blob: Dict[str, Any]) -> None:
        import copy

        super().load_state_dict(blob)
        self.configs = list(blob["configs"])
        self._ctrl_latest = dict(blob["ctrl_latest"])
        # Copy (never alias) so re-loading the same blob starts from the
        # checkpoint, not from this incarnation's later mutations.
        self.reps = copy.deepcopy(blob["reps"])
        # Pending-op tickets in the checkpoint are deepcopy clones — the
        # driver's payload bindings hold *different* ticket objects, so
        # an eviction after restore would resolve the payload's clone
        # while rep.pending_* stayed live forever, wedging orchestration.
        # Clear them: re-proposal is idempotent (config-num and
        # shard-state gates make duplicates no-ops).
        for rep in self.reps.values():
            rep.pending_config = None
            rep.pending_insert.clear()
            rep.pending_delete.clear()
            rep.pending_confirm.clear()
        # copy=True: never alias the unpickled buffer (host.py restore
        # explains the donation hazard).
        self._route = jnp.array(blob["route"], copy=True)
        self._ctrl_cmd = blob["ctrl_cmd"]
        self._orchestrate_enabled = blob["orchestrate"]
        # gid → engine-group mapping travels with the checkpoint (older
        # blobs predate fleet mode: identity mapping).  A checkpoint
        # whose gid set diverges from the constructor's is refused loudly
        # — silently adopting it would keep serving the old assignment
        # while peers/routing were built from the new spec (same
        # loud-beats-lucky stance as EngineDriver.restore's mesh check).
        saved_gids = blob.get("gids")
        if saved_gids is not None and sorted(saved_gids) != sorted(self.gids):
            raise ValueError(
                f"checkpoint hosts gids {list(saved_gids)} but this "
                f"instance was built for gids {self.gids}; restart with "
                "the checkpoint's gid set (or a fresh data dir)"
            )
        # Restore the checkpoint's gid→engine-group mapping: after
        # adopt/drop (placement layer) it is no longer the constructor's
        # enumeration order.  Older blobs lack "g2l": the constructor's
        # mapping stands (and the list-equality guard above kept order).
        saved_g2l = blob.get("g2l")
        if saved_g2l is not None:
            self.gids = list(saved_gids)
            self._g2l = {int(g): int(l) for g, l in saved_g2l.items()}
            self._l2g = {l: g for g, l in self._g2l.items()}
        elif saved_gids is not None and list(saved_gids) != self.gids:
            raise ValueError(
                "checkpoint predates the placement layer but its gid "
                "ORDER diverges from this instance's; restart with the "
                "checkpoint's gid order"
            )

    # -- client/admin surface ---------------------------------------------

    def submit(self, gid: int, op: str, key: str, value: str = "",
               client_id: int = 0, command_id: int = 0) -> ShardTicket:
        t = ShardTicket(group=gid)
        self.driver.start(
            self._g2l[gid],
            _ClientOp(op=op, key=key, value=value, client_id=client_id,
                      command_id=command_id, ticket=t),
        )
        return t

    def delete_shard(self, src_gid: int, shard: int,
                     config_num: int) -> ShardTicket:
        """Propose Challenge-1 deletion in a *local* old owner's log on
        behalf of a remote puller — the serving side of a fleet peer's
        ``remote_delete`` (the cross-process form of orchestration
        step (c) below)."""
        t = ShardTicket(group=src_gid)
        self.driver.start(
            self._g2l[src_gid],
            _DeleteOp(config_num=config_num, shard=shard, ticket=t),
        )
        return t

    def confirm_shard(self, gid: int, shard: int,
                      config_num: int) -> ShardTicket:
        """Propose a GC confirm (GCING→SERVING) directly in ``gid``'s
        log — the recovery path's re-application of a confirm the WAL
        proves already committed pre-crash (the delete leg of the
        handshake already ran then; re-running it against a possibly
        unreachable peer would wedge replay).  Idempotent: a no-op when
        the slot is past GCING or the config has moved on."""
        t = ShardTicket(group=gid)
        self.driver.start(
            self._g2l[gid],
            _ConfirmOp(config_num=config_num, shard=shard, ticket=t),
        )
        return t

    def _ctrl(self, kind: str, arg: Any,
              command_id: Optional[int] = None,
              client_id: Optional[int] = None) -> ShardTicket:
        """Propose a ctrler op.  Pass the ``command_id`` of a failed
        ticket to retry it — the ctrler dedup table then guarantees
        exactly-once application even if the original did commit.
        ``client_id`` overrides the session the dedup keys on: a
        network admin clerk passes ITS unique id so its (id, cmd)
        pairs can never collide with another clerk's (or another
        process's) numbering — see split_shard_server.admin."""
        if command_id is None:
            self._ctrl_cmd += 1
            command_id = self._ctrl_cmd
        else:
            # Keep the auto counter ahead of externally supplied ids
            # (fleet admin) — otherwise a later auto-allocated id lands
            # below _ctrl_latest and is silently dedup-dropped as OK.
            self._ctrl_cmd = max(self._ctrl_cmd, command_id)
        if client_id is None:
            client_id = self._ctrl_client_id
        t = ShardTicket(group=0, command_id=command_id)
        self.driver.start(
            0, _CtrlOp(kind=kind, arg=arg, client_id=client_id,
                       command_id=command_id, ticket=t)
        )
        return t

    def join(self, gids: List[int],
             command_id: Optional[int] = None) -> ShardTicket:
        """Add replica groups (reference: shardctrler Join).  Group
        "server names" are synthesized from the engine group index."""
        servers = {g: [f"engine-group-{g}"] for g in gids}
        return self._ctrl("join", servers, command_id)

    def leave(self, gids: List[int],
              command_id: Optional[int] = None) -> ShardTicket:
        return self._ctrl("leave", list(gids), command_id)

    def move(self, shard: int, gid: int,
             command_id: Optional[int] = None) -> ShardTicket:
        return self._ctrl("move", (shard, gid), command_id)

    def query_latest(self) -> Config:
        """Latest *committed* config (direct read of the applied config
        RSM — the in-process form of the clerk's Query)."""
        return self.configs[-1].clone()

    def get_fast(self, key: str) -> ShardTicket:
        """Linearizable read served from the applied frontier WITHOUT a
        log entry — the sharded form of ``BatchedKV.get``'s ReadIndex
        collapse (this service is the sole acker of every write across
        all groups, so the applied frontier covers every acknowledged
        op), additionally gated on shard ownership exactly like the
        logged path's apply-time re-check: only a replica whose applied
        config owns the shard in a serving state may answer
        (`_apply_client` above; Challenge 2 gate).  During migration the
        caller sees ``ErrWrongGroup`` and retries, as with logged ops."""
        shard = key2shard(key)
        # Host-side routing: configs[-1].shards and _route are assigned
        # together in _apply_ctrl, and a device readback here would put
        # a sync on the zero-device-work path.
        gid = self.configs[-1].shards[shard]
        t = ShardTicket(group=gid, done=True, done_tick=self.driver.tick)
        rep = self.reps.get(gid)
        if rep is None or not rep.can_serve(shard):
            t.err = ERR_WRONG_GROUP
            return t
        sh = rep.shards[shard]
        if key in sh.data:
            t.value = sh.data[key]
        else:
            t.err = ERR_NO_KEY
        return t

    def shard_table(self) -> jnp.ndarray:
        """Device shard→gid routing table for :func:`route_keys`."""
        return self._route

    # -- group placement (whole-group migration between fleet processes) --
    #
    # The placement controller (distributed/placement.py) moves a whole
    # raft group between processes: seal+export at the source, adopt
    # into a spare engine slot at the destination, drop at the source.
    # Sealing freezes the replica without draining: every post-seal
    # apply is a WRONG_GROUP no-op (can_serve is False), unacked, so
    # clients retry at the destination and the per-shard dedup tables —
    # which travel inside the blob — keep the retries exactly-once.

    def free_slots(self) -> int:
        """Spare engine groups available for :meth:`adopt_gid`."""
        return (self.driver.cfg.G - 1) - len(self._g2l)

    def is_sealed(self, gid: int) -> bool:
        rep = self.reps.get(gid)
        return rep is not None and getattr(rep, "sealed", False)

    # -- replica membership (engine/host.py joint consensus) --------------
    #
    # Gid-level facades over the EngineDriver admin ops, shaped for the
    # placement controller's replace-dead-replica legs: every verb is
    # idempotent (a retried leg after a controller crash or lost reply
    # answers the same way), and ``begin_joint_gid`` treats "already in
    # joint toward this target" / "already settled at this target" as
    # success rather than the engine's one-change-at-a-time refusal.

    def replica_health(self, gid: int) -> Optional[Dict[str, Any]]:
        """Per-replica liveness + the group's voter sets: ``{"alive":
        [bool]*P, "voters_old", "voters_new", "joint", "epoch"}`` —
        the controller's dead-voter detection signal.  The config view
        is the leader's when one exists (max across rows otherwise:
        mid-election health must still name the voters)."""
        g = self._g2l.get(gid)
        if g is None:
            return None
        d = self.driver
        st = d.np_state()
        lead = d.leader_of(g)
        row = lead if lead is not None else int(
            (st["voters_old"][g] | st["voters_new"][g]).argmax()
        )
        unpack = lambda b: sorted(
            q for q in range(d.cfg.P) if (int(b) >> q) & 1
        )
        return {
            "alive": st["alive"][g].astype(bool).tolist(),
            "voters_old": unpack(st["voters_old"][g, row]),
            "voters_new": unpack(st["voters_new"][g, row]),
            "joint": bool(st["joint"][g].any()),
            "epoch": int(st["cfg_epoch"][g, row]),
            "leader": -1 if lead is None else int(lead),
        }

    def config_of_gid(self, gid: int) -> Optional[Dict[str, Any]]:
        g = self._g2l.get(gid)
        if g is None:
            return None
        try:
            return self.driver.config_of(g)
        except RuntimeError:
            return None  # no leader right now: caller retries

    def add_learner_gid(self, gid: int, p: int) -> bool:
        """Seat ``p`` as a fresh learner of ``gid``.  Idempotent: if
        ``p`` is already a live non-voter (a previous attempt landed
        but the reply was lost), answers True without re-wiping it —
        a re-wipe mid-catch-up would discard replication progress."""
        g = self._g2l.get(gid)
        if g is None:
            return False
        d = self.driver
        st = d.np_state()
        lead = d.leader_of(g)
        if lead is None:
            return False
        voter = ((int(st["voters_old"][g, lead])
                  | int(st["voters_new"][g, lead])) >> p) & 1
        if not voter and bool(st["alive"][g, p]):
            return True  # already seated by a prior attempt
        try:
            d.add_learner(g, p)
        except (RuntimeError, ValueError):
            return False
        return True

    def learner_match_gid(self, gid: int, p: int) -> Optional[tuple]:
        g = self._g2l.get(gid)
        if g is None:
            return None
        try:
            return self.driver.learner_match(g, p)
        except RuntimeError:
            return None

    def begin_joint_gid(self, gid: int, new_voters) -> bool:
        """Enter the joint phase toward ``new_voters``.  Idempotent:
        already joint toward this exact target, or already settled AT
        the target, answers True — the controller's crash-resume
        re-drive of a leg whose first attempt landed."""
        g = self._g2l.get(gid)
        if g is None:
            return False
        target = sorted(set(int(q) for q in new_voters))
        c = self.config_of_gid(gid)
        if c is None:
            return False
        if c["joint"] and c["voters_new"] == target:
            return True
        if not c["joint"] and c["voters_old"] == target:
            return True  # transition already completed
        try:
            self.driver.begin_joint(g, target)
        except (RuntimeError, ValueError):
            return False
        return True

    def kill_replica_gid(self, gid: int, p: int) -> bool:
        """Permanently kill replica row ``p`` of ``gid`` (the nemesis
        verb behind replace-dead-replica chaos: the row stays dead
        until a reconfig reseats the slot as a fresh incarnation)."""
        g = self._g2l.get(gid)
        if g is None:
            return False
        self.driver.set_alive(g, int(p), False)
        return True

    def export_group(self, gid: int) -> Optional[Dict[str, Any]]:
        """Seal ``gid`` and return its serialized applied state, or
        ``None`` if it cannot seal yet (mid-migration, config proposal
        in flight, or behind the latest config — the caller retries).
        Idempotent: an already-sealed group returns the same frozen
        state again (the seal stops every mutation), so a lost reply
        costs nothing."""
        rep = self.reps.get(gid)
        if rep is None:
            return None
        if not getattr(rep, "sealed", False):
            if self._live(rep.pending_config):
                return None
            if any(sh.state != SERVING for sh in rep.shards.values()):
                return None
            if self.configs[-1].num > rep.cur.num:
                return None  # catching up; export the settled state
            rep.sealed = True
        # Once the blob is returned it may be handed to an adopt RPC;
        # from here on only a force-unseal may revive this replica.
        rep.export_dispatched = True
        return {
            "gid": gid,
            "cur": rep.cur.clone(),
            "prev": rep.prev.clone(),
            "shards": {
                s: (sh.state, dict(sh.data), dict(sh.latest))
                for s, sh in rep.shards.items()
            },
        }

    def snapshot_group(self, gid: int) -> Optional[Dict[str, Any]]:
        """Non-sealing export: a deep-copied :meth:`export_group`-shaped
        blob of ``gid``'s applied state, or ``None`` while the group is
        mid-migration / behind config (same stability preconditions as
        export, so the blob never captures a half-applied handoff).
        The state-plane shipper calls this on a cadence — the group
        keeps serving, so the copy is only a point-in-time snapshot and
        the shipped WAL tail covers the writes after it."""
        rep = self.reps.get(gid)
        if rep is None or getattr(rep, "sealed", False):
            return None
        if self._live(rep.pending_config):
            return None
        if any(sh.state != SERVING for sh in rep.shards.values()):
            return None
        if self.configs[-1].num > rep.cur.num:
            return None
        return {
            "gid": gid,
            "cur": rep.cur.clone(),
            "prev": rep.prev.clone(),
            "shards": {
                s: (sh.state, dict(sh.data), dict(sh.latest))
                for s, sh in rep.shards.items()
            },
        }

    def unseal_group(self, gid: int, force: bool = False) -> None:
        """Abort a migration whose blob was NEVER dispatched to a
        destination — once an adopt RPC may have been dispatched,
        unsealing would fork the group (two serving copies), so a
        post-dispatch unseal raises unless the caller proves the
        destination can never adopt (``force=True``, the controller's
        dead-destination resume leg)."""
        rep = self.reps.get(gid)
        if rep is None:
            return
        if (getattr(rep, "sealed", False)
                and getattr(rep, "export_dispatched", False)
                and not force):
            raise RuntimeError(
                f"gid {gid}: export blob already dispatched — unsealing "
                "could fork the group; pass force=True only when the "
                "destination is provably dead"
            )
        rep.sealed = False
        rep.export_dispatched = False

    def adopt_gid(self, gid: int, blob: Optional[Dict[str, Any]] = None) -> int:
        """Host ``gid`` in a spare engine slot.  ``blob`` is a frozen
        :meth:`export_group` state; ``None`` adopts EMPTY (dead-source
        failover): the fresh replica starts AT the latest config with
        empty SERVING shards rather than replaying the config history —
        it holds no data to hand off, the historical handoffs happened
        in the group's previous incarnation (whose peers will never
        re-run them), and a replay would wedge the leaving-shard slots
        in BEPULLING forever waiting for delete requests that were
        already sent and answered.  The group's own shard data died
        with its process (the non-durable fleet crash model; see the
        placement module docstring).  Returns the local engine group
        index."""
        if gid == 0:
            raise ValueError("engine group 0 is the config RSM")
        if gid in self._g2l:
            raise ValueError(f"gid {gid} already hosted here")
        used = set(self._g2l.values())
        free = [l for l in range(1, self.driver.cfg.G) if l not in used]
        if not free:
            raise RuntimeError(
                f"no spare engine slot for gid {gid} "
                f"(G={self.driver.cfg.G}, hosting {sorted(self._g2l)})"
            )
        loc = free[0]
        rep = _Replica(gid)
        if blob is not None:
            rep.cur = blob["cur"].clone()
            rep.prev = blob["prev"].clone()
            for s, (state, data, latest) in blob["shards"].items():
                rep.shards[int(s)] = _ShardSlot(
                    state=state, data=dict(data), latest=dict(latest)
                )
        else:
            latest = self.query_latest()
            rep.cur = latest.clone()
            rep.prev = rep.cur
        # Bounded by construction: the free-slot check above caps
        # hosted groups at the engine's fixed G-1 slots.
        self.gids.append(gid)  # graftlint: disable=unbounded-queue
        self._g2l[gid] = loc
        self._l2g[loc] = gid
        self.reps[gid] = rep
        return loc

    def group_quiesced(self, gid: int) -> bool:
        """True when ``gid``'s slot has applied everything committed —
        the :meth:`drop_gid` gate (a sealed group's tail applies are
        WRONG_GROUP no-ops, but they must RESOLVE before the slot is
        reused or their tickets would wedge)."""
        loc = self._g2l[gid]
        commit = int(
            np.asarray(self.driver.last_metrics["commit_index"])[loc]
        )
        return self.applied_upto[loc] >= commit

    def drop_gid(self, gid: int) -> None:
        """Free ``gid``'s engine slot after a migration (or an abandoned
        adoption).  Callers pump until :meth:`group_quiesced` first.
        Entries accepted-but-uncommitted in the old log may still commit
        after the slot is re-adopted — they apply against the NEW gid's
        replica as WRONG_GROUP no-ops (its config does not assign their
        shards to it), so slot reuse is safe."""
        loc = self._g2l.pop(gid)
        del self._l2g[loc]
        self.gids.remove(gid)
        del self.reps[gid]

    # -- admin convenience (pump until the ctrler op commits) -------------

    def admin_sync(self, kind: str, arg: Any, max_ticks: int = 3000) -> None:
        mk = {
            "join": lambda cid: self.join(arg, command_id=cid),
            "leave": lambda cid: self.leave(arg, command_id=cid),
            "move": lambda cid: self.move(*arg, command_id=cid),
        }[kind]
        t = mk(None)
        waited = 0
        while waited < max_ticks:
            self.pump(5)
            waited += 5
            if t.done and not t.failed:
                return
            if t.failed:
                t = mk(t.command_id)  # retry under the same dedup id
        raise TimeoutError(f"ctrler {kind} did not commit in {max_ticks} ticks")

    # -- pumping (frontier/sweep machinery in FrontierService) -------------

    def pump(self, n_ticks: int = 1, orchestrate: bool = True) -> None:
        self._orchestrate_enabled = orchestrate
        super().pump(n_ticks)

    def after_step(self, n_ticks: int = 1, orchestrate=None) -> None:
        """Pipelined-pump entry (FrontierService.after_step): the
        engine advance happened at dispatch; this is the host half.
        ``orchestrate=None`` keeps the gate :meth:`pump` set (the base
        pump routes through here), a bool overrides it — the pipelined
        serving loop passes True explicitly."""
        if orchestrate is not None:
            self._orchestrate_enabled = orchestrate
        super().after_step(n_ticks)

    def _post_pump(self) -> None:
        if self._orchestrate_enabled:
            self._orchestrate()

    def _on_evicted(self, payload: Any) -> None:
        if isinstance(payload, PayloadSlice):
            # Firehose rows that lost their slots: the CLIENT retries
            # them (row-level RETRY errs; per-shard session dedup keeps
            # the retry exactly-once even across a migration, because
            # the dedup tables travel with the shard).
            payload.frame.rows_failed(payload.rows)
            return
        t = getattr(payload, "ticket", None)
        if t is not None and not t.done:
            t.done = True
            t.failed = True

    # -- columnar firehose (engine/firehose.py) --------------------------

    def submit_frame(self, blob: bytes) -> FirehoseFrame:
        """Columnar frame for the SHARDED service: the ``group`` column
        carries GLOBAL gids (the client routes key→shard→gid from its
        config, reference clerk loop shardkv/client.go:68-129); rows
        addressed to a gid this instance does not host resolve
        immediately as WRONG_GROUP (the client re-queries the config
        and re-routes).  Write rows enter each local group's log as
        contiguous runs; ownership is re-checked per row AT APPLY TIME
        (`_apply_slice`), exactly like the per-op path."""
        f = FirehoseFrame(blob, self.driver.tick)
        wr = f.write_rows
        if not len(wr):
            return f
        gids = f.groups[wr]
        local = np.full(len(gids), -1, np.int64)
        for gid, loc in self._g2l.items():
            local[gids == gid] = loc
        bad = wr[local < 0]
        if len(bad):
            f.rows_done(bad, np.full(len(bad), FH_WRONG_GROUP, np.uint8))
        good_rows = wr[local >= 0]
        good_local = local[local >= 0]
        if not len(good_rows):
            return f
        order = np.argsort(good_local, kind="stable")
        rows_sorted = good_rows[order]
        gs = good_local[order]
        bounds = np.nonzero(np.diff(gs))[0] + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [len(gs)]])
        for s, e in zip(starts.tolist(), ends.tolist()):
            self.driver.start_run(int(gs[s]), f, rows_sorted[s:e])
        return f

    def _apply_slice(self, g: int, idx: int, sl, now: int) -> None:
        """Bulk apply of one committed firehose slice to a replica
        group: per row the kvraft-with-shards semantics (ownership
        gate + per-shard dup table + mutate — `_apply_client`);
        everything around them per-slice."""
        assert g != 0, "the config RSM's log never carries firehose rows"
        f = sl.frame
        gid = self._l2g.get(g)
        if gid is None:
            self._on_evicted(sl)  # slot freed by drop_gid (see _apply)
            return
        rep = self.reps[gid]
        errs = np.empty(len(sl.rows), np.uint8)
        ops_l = f.ops_l
        keys = f.keys
        vals = f.vals
        clients_l = f.clients_l
        commands_l = f.commands_l
        on_write = self.on_write
        for j, r in enumerate(sl.rows.tolist()):
            key = keys[r]
            shard = key2shard(key)
            if not rep.can_serve(shard):
                errs[j] = FH_WRONG_GROUP
                continue
            sh = rep.shards[shard]
            cid = clients_l[r]
            cmd = commands_l[r]
            if cmd > 0 and sh.latest.get(cid, -1) >= cmd:
                errs[j] = FH_OK  # duplicate write: already applied
                continue
            if ops_l[r] == OP_PUT:
                sh.data[key] = vals[r]
            else:
                sh.data[key] = sh.data.get(key, "") + vals[r]
            if cmd > 0:
                sh.latest[cid] = cmd
            if on_write is not None:
                on_write(rep.gid, _ClientOp(
                    op=PUT if ops_l[r] == OP_PUT else APPEND,
                    key=key, value=vals[r], client_id=cid, command_id=cmd,
                ))
            errs[j] = FH_OK
        f.rows_done(sl.rows, errs)

    # -- apply path --------------------------------------------------------

    def _resolve(self, op: Any, now: int, err: str = OK, value: str = "") -> None:
        t = op.ticket
        if t is not None and not t.done:
            t.done = True
            t.err = err
            t.value = value
            t.done_tick = now

    def _apply(self, g: int, idx: int, op: Any, now: int) -> None:
        if op is None:
            return  # binding lost to a leader change before commit
        if g == 0:
            self._apply_ctrl(op, now)
        else:
            gid = self._l2g.get(g)
            if gid is None:
                # Slot freed by drop_gid: an accepted-but-uncommitted
                # tail entry committed late.  Its group is gone — fail
                # the ticket so the caller re-routes.
                self._on_evicted(op)
                return
            self._apply_replica(self.reps[gid], op, now)

    def _apply_ctrl(self, op: Any, now: int) -> None:
        if not isinstance(op, _CtrlOp):
            return
        if self._ctrl_latest.get(op.client_id, -1) >= op.command_id:
            self._resolve(op, now)  # duplicate join/leave/move: no-op
            return
        self._ctrl_latest[op.client_id] = op.command_id
        cfg = self.configs[-1].clone()
        cfg.num += 1
        if op.kind == "join":
            cfg.groups.update({g: list(s) for g, s in op.arg.items()})
            cfg.shards = rebalance(cfg.shards, cfg.groups)
        elif op.kind == "leave":
            for gid in op.arg:
                cfg.groups.pop(gid, None)
            cfg.shards = rebalance(cfg.shards, cfg.groups)
        else:  # move
            shard, gid = op.arg
            cfg.shards[shard] = gid
        self.configs.append(cfg)
        self._route = jnp.asarray(np.array(cfg.shards, np.int32))
        if self.on_ctrl is not None:
            self.on_ctrl(op)
        self._resolve(op, now)

    def _apply_replica(self, rep: _Replica, op: Any, now: int) -> None:
        if isinstance(op, _ClientOp):
            self._apply_client(rep, op, now)
        elif isinstance(op, _ConfigOp):
            # Strictly in-order, never mid-migration
            # (mirror of services/shardkv.py:459-477).  A sealed replica
            # is frozen: its exported blob must not race a config flip.
            if (
                not getattr(rep, "sealed", False)
                and op.config.num == rep.cur.num + 1
                and all(
                    sh.state == SERVING for sh in rep.shards.values()
                )
            ):
                rep.prev = rep.cur
                rep.cur = op.config
                for s in range(NSHARDS):
                    was = rep.prev.shards[s] == rep.gid
                    mine = op.config.shards[s] == rep.gid
                    if mine and not was:
                        rep.shards[s].state = (
                            SERVING if rep.prev.shards[s] == 0 else PULLING
                        )
                    elif was and not mine:
                        rep.shards[s].state = BEPULLING
            rep.pending_config = None
            self._resolve(op, now)
        elif isinstance(op, _InsertOp):
            sh = rep.shards[op.shard]
            if op.config_num == rep.cur.num and sh.state == PULLING:
                sh.data = dict(op.data)
                sh.latest = dict(op.latest)
                sh.state = GCING  # serve before the old copy is deleted
                if self.on_insert is not None:
                    self.on_insert(rep.gid, op.shard, op.config_num,
                                   sh.data, sh.latest)
            rep.pending_insert.pop(op.shard, None)
            self._resolve(op, now)
        elif isinstance(op, _DeleteOp):
            # Runs in the OLD owner's log.  ErrNotReady if this group
            # hasn't seen the config yet (it would still be serving).
            if op.config_num > rep.cur.num:
                self._resolve(op, now, err=ERR_NOT_READY)
                return
            if op.config_num == rep.cur.num:
                sh = rep.shards[op.shard]
                if sh.state == BEPULLING:
                    rep.shards[op.shard] = _ShardSlot()  # Challenge 1
                    if self.on_delete is not None:
                        self.on_delete(rep.gid, op.shard, op.config_num)
            self._resolve(op, now)  # < cur.num: already gone, idempotent
        elif isinstance(op, _ConfirmOp):
            sh = rep.shards[op.shard]
            if op.config_num == rep.cur.num and sh.state == GCING:
                sh.state = SERVING
                if self.on_confirm is not None:
                    self.on_confirm(rep.gid, op.shard, op.config_num)
            rep.pending_confirm.pop(op.shard, None)
            self._resolve(op, now)

    def _apply_client(self, rep: _Replica, op: _ClientOp, now: int) -> None:
        shard = key2shard(op.key)
        sh = rep.shards[shard]
        # Ownership re-checked at apply time: the config may have moved
        # between proposal and commit (reference: shardkv apply path).
        if not rep.can_serve(shard):
            self._resolve(op, now, err=ERR_WRONG_GROUP)
            return
        if op.op != GET and sh.latest.get(op.client_id, -1) >= op.command_id:
            self._resolve(op, now)  # duplicate write: already applied
            return
        if op.op == GET:
            if op.key in sh.data:
                self._resolve(op, now, value=sh.data[op.key])
            else:
                self._resolve(op, now, err=ERR_NO_KEY)
            return
        if op.op == PUT:
            sh.data[op.key] = op.value
        else:
            sh.data[op.key] = sh.data.get(op.key, "") + op.value
        sh.latest[op.client_id] = op.command_id
        if self.on_write is not None:
            self.on_write(rep.gid, op)
        self._resolve(op, now)

    # -- migration orchestration (the batched form of the tickers) ---------

    @staticmethod
    def _live(t: Optional[ShardTicket]) -> bool:
        return t is not None and not t.done

    # Ticks a proposal batch may sit unresolved before _orchestrate
    # abandons and re-proposes it.  Liveness, not correctness: an entry
    # accepted under a leader that then lost quorum keeps its old term
    # after the next election, and Raft's commit rule never counts it —
    # only a NEW current-term entry drags it over the commit line.  An
    # idle group generates none (payload bindings are index-keyed, so
    # the kernel cannot inject a leader no-op), and every orchestrate
    # verb is gated on the live ticket — a deadlock observed as a
    # revived group stuck one config behind forever.  Re-proposing is
    # safe: every internal op is config-num/state gated, so the stale
    # duplicate applies as a no-op and still resolves its ticket.
    PROPOSAL_STALL_TICKS = 200

    def _orchestrate(self) -> None:
        latest = self.configs[-1]
        for gid in list(self.gids):
            rep = self.reps[gid]
            if getattr(rep, "sealed", False):
                continue  # frozen for export: no proposals of any kind
            pend = [rep.pending_config,
                    *rep.pending_insert.values(),
                    *rep.pending_delete.values(),
                    *rep.pending_confirm.values()]
            if not any(self._live(t) for t in pend):
                rep.pending_since = 0
            elif getattr(rep, "pending_since", 0) == 0:
                rep.pending_since = self.driver.tick
            elif (
                self.driver.tick - rep.pending_since
                > self.PROPOSAL_STALL_TICKS
            ):
                rep.pending_config = None
                rep.pending_insert.clear()
                rep.pending_delete.clear()
                rep.pending_confirm.clear()
                rep.pending_since = 0
            # (a) config advance — only participating (or about to
            # participate) groups need to track configs.
            if (
                latest.num > rep.cur.num
                and not self._live(rep.pending_config)
                and all(sh.state == SERVING for sh in rep.shards.values())
            ):
                nxt = self.configs[rep.cur.num + 1].clone()
                t = ShardTicket(group=gid)
                rep.pending_config = t
                self.driver.start(self._g2l[gid], _ConfigOp(config=nxt, ticket=t))
            # (b) shard pull: read the source group's applied state once
            # it has applied the same config (the ErrNotReady gate).  A
            # source gid hosted by another fleet process goes through
            # the remote_fetch hook instead of the direct host read.
            for s in range(NSHARDS):
                sh = rep.shards[s]
                if sh.state == PULLING and not self._live(
                    rep.pending_insert.get(s)
                ):
                    if self.migration_paused:
                        continue  # recovery: no pulls until redo completes
                    src_gid = rep.prev.shards[s]
                    src = self.reps.get(src_gid)
                    if src is not None:
                        if src.cur.num < rep.cur.num:
                            continue  # source hasn't caught up; retry later
                        pull_data = dict(src.shards[s].data)
                        pull_latest = dict(src.shards[s].latest)
                    elif self.remote_fetch is not None:
                        got = self.remote_fetch(src_gid, s, rep.cur.num)
                        if got is None:
                            continue  # RPC in flight / source not ready
                        pull_data, pull_latest = dict(got[0]), dict(got[1])
                    else:
                        continue  # source unknown and no fleet hook
                    t = ShardTicket(group=gid)
                    rep.pending_insert[s] = t
                    self.driver.start(
                        self._g2l[gid],
                        _InsertOp(
                            config_num=rep.cur.num,
                            shard=s,
                            data=pull_data,
                            latest=pull_latest,
                            ticket=t,
                        ),
                    )
                # (c) GC handshake: delete at the old owner, then
                # confirm locally (Challenge 1).  A remote old owner is
                # deleted through the remote_delete hook — Challenge 1
                # crosses process boundaries too.
                elif sh.state == GCING:
                    if self.migration_paused:
                        continue  # recovery: WAL confirm records stand in
                    dt = rep.pending_delete.get(s)
                    if dt is None or (dt.done and (dt.failed or dt.err != OK)):
                        src_gid = rep.prev.shards[s]
                        if src_gid in self.reps:
                            t = ShardTicket(group=src_gid)
                            rep.pending_delete[s] = t
                            self.driver.start(
                                self._g2l[src_gid],
                                _DeleteOp(config_num=rep.cur.num, shard=s,
                                          ticket=t),
                            )
                        elif self.remote_delete is not None:
                            st = self.remote_delete(src_gid, s, rep.cur.num)
                            if st is not None:
                                # Done ticket carries the outcome; a
                                # not-ready outcome re-enters this branch
                                # next sweep and re-asks the hook.
                                rep.pending_delete[s] = ShardTicket(
                                    group=src_gid, done=True,
                                    err=OK if st else ERR_NOT_READY,
                                )
                        else:
                            # No fleet: an unknown source was never
                            # joined here — nothing to delete.
                            rep.pending_delete[s] = ShardTicket(
                                group=0, done=True, err=OK
                            )
                    elif (
                        dt.done
                        and dt.err == OK
                        and not self._live(rep.pending_confirm.get(s))
                    ):
                        t = ShardTicket(group=gid)
                        rep.pending_confirm[s] = t
                        self.driver.start(
                            self._g2l[gid],
                            _ConfirmOp(config_num=rep.cur.num, shard=s,
                                       ticket=t),
                        )
                elif sh.state == SERVING:
                    rep.pending_delete.pop(s, None)


class BatchedShardClerk:
    """Client of :class:`BatchedShardKV` with the reference clerk's
    retry loop (re-query config on ErrWrongGroup, resubmit on lost
    leadership; reference: shardkv/client.go:68-129) and optional
    porcupine recording on sampled shards."""

    def __init__(
        self,
        skv: BatchedShardKV,
        client_id: int,
        record_shards: Optional[List[int]] = None,
    ) -> None:
        self.skv = skv
        self.client_id = client_id
        self.command_id = 0
        self._record = set(record_shards or [])
        self.histories: Dict[int, List[Operation]] = {
            s: [] for s in self._record
        }

    # -- async sessions (for concurrent-client tests) ----------------------

    # Ticks before an unresolved ticket is re-submitted under the same
    # (client_id, command_id).  A ticket can wedge forever without
    # this: if its entry is truncated by a leader change, the ticket
    # only fails when a new acceptance re-binds its log index — which
    # never happens once client traffic drains.  The reference clerk's
    # timeout-retry loop (shardkv/client.go:68-129) covers the same
    # hole; dedup makes the duplicate harmless.
    RESUBMIT_TICKS = 300

    class Session:
        def __init__(self, clerk: "BatchedShardClerk", op: str, key: str,
                     value: str, command_id: int) -> None:
            self.clerk = clerk
            self.op, self.key, self.value = op, key, value
            self.command_id = command_id
            self.call_tick = clerk.skv.driver.tick
            self.submit_tick = self.call_tick
            self.ticket: Optional[ShardTicket] = None
            self.done = False
            self.result = ""
            self._submit()

        def _submit(self) -> None:
            self.submit_tick = self.clerk.skv.driver.tick
            cfg = self.clerk.skv.query_latest()
            gid = cfg.shards[key2shard(self.key)]
            if gid not in self.clerk.skv.reps:
                self.ticket = None  # shard unassigned; retry after pump
                return
            self.ticket = self.clerk.skv.submit(
                gid, self.op, self.key, self.value,
                client_id=self.clerk.client_id, command_id=self.command_id,
            )

        def poll(self) -> bool:
            """Advance after a pump; True when the op has a final reply."""
            if self.done:
                return True
            t = self.ticket
            if t is None:
                self._submit()
                return False
            if not t.done:
                tick = self.clerk.skv.driver.tick
                if tick - self.submit_tick >= BatchedShardClerk.RESUBMIT_TICKS:
                    self._submit()  # wedged ticket: retry, dedup-safe
                return False
            if t.failed or t.err == ERR_WRONG_GROUP:
                self._submit()  # same command_id: dedup makes it safe
                return False
            self.done = True
            self.result = t.value if t.err == OK else ""
            self.clerk._record_op(self)
            return True

    def begin(self, op: str, key: str, value: str = "") -> "Session":
        self.command_id += 1
        return self.Session(self, op, key, value, self.command_id)

    def _record_op(self, s: "Session") -> None:
        shard = key2shard(s.key)
        if shard in self._record:
            self.histories[shard].append(
                Operation(
                    client_id=self.client_id,
                    input=KvInput(op=_PORCUPINE_OPCODE[s.op], key=s.key,
                                  value=s.value),
                    call=float(s.call_tick),
                    output=KvOutput(value=s.result),
                    ret=float(self.skv.driver.tick) + 0.5,
                )
            )

    def get_fast(self, key: str, max_ticks: int = 4000) -> str:
        """ReadIndex fast read with the clerk retry loop: instant when
        the routed owner is serving; pumps through migration windows
        (ErrWrongGroup) like any other clerk op.  Recorded in the
        porcupine history with its full call→return interval."""
        call = self.skv.driver.tick
        waited = 0
        while True:
            t = self.skv.get_fast(key)
            if t.err in (OK, ERR_NO_KEY):
                value = t.value if t.err == OK else ""
                shard = key2shard(key)
                if shard in self._record:
                    self.histories[shard].append(
                        Operation(
                            client_id=self.client_id,
                            input=KvInput(op=OP_GET, key=key),
                            call=float(call),
                            output=KvOutput(value=value),
                            ret=float(self.skv.driver.tick) + 0.5,
                        )
                    )
                return value
            if waited >= max_ticks:
                raise TimeoutError(
                    f"get_fast({key!r}): no serving owner in {max_ticks} ticks"
                )
            self.skv.pump(5)
            waited += 5

    # -- blocking convenience ----------------------------------------------

    def _run(self, op: str, key: str, value: str = "",
             max_ticks: int = 4000) -> str:
        s = self.begin(op, key, value)
        waited = 0
        while waited < max_ticks:
            self.skv.pump(5)
            waited += 5
            if s.poll():
                return s.result
        raise TimeoutError(f"{op}({key!r}) unresolved after {max_ticks} ticks")

    def get(self, key: str) -> str:
        return self._run(GET, key)

    def put(self, key: str, value: str) -> None:
        self._run(PUT, key, value)

    def append(self, key: str, value: str) -> None:
        self._run(APPEND, key, value)
