"""Post-hoc verification + measured latency for the headline bench.

Input: the per-tick device records of :func:`core.run_ticks_traced`
(per-group ingest/commit frontiers and accept terms), concatenated
over the timed chunks.  Two consumers:

* :func:`latency_histogram` — the MEASURED per-entry commit-latency
  distribution, in ticks, exact for every entry committed in the
  window.  Calm groups (no leader rebind) are counted by overlap
  algebra on the frontier curves: the entries ingested at tick ``s``
  and committed at tick ``t`` are the interval intersection
  ``(I[s-1], I[s]] ∩ (C[t-1], C[t]]``, so a handful of vectorized
  passes count 40M+ entries exactly, no per-entry loop.  CHURNED
  groups (a mid-window leader change rebinds indices, breaking the
  monotone-frontier assumption) are detected vectorized and measured
  exactly per entry from their accept-event bindings — nothing is
  silently dropped; the residual ``unaccounted`` count is reported.

* :func:`verify_sampled_groups` — the north star's "porcupine-verified
  on sampled shards" applied to the flagship run itself (reference
  pattern: the kvraft harness checks the history of the actual run,
  kvraft/test_test.go:365-381).  Each sampled group's operation
  history is reconstructed from what the device recorded — every
  accepted command becomes an Append whose call time is its ingest
  tick and return time its commit tick.  Leader rebinds are resolved
  from the accept-term records: an index bound at two terms is
  arbitrated against the final device ring where the ring still covers
  it, and conservatively widened to its earliest binding otherwise
  (reported, never silently skipped).  The reconstruction is
  cross-checked entry-for-entry against the final device ring, then
  checked with the same porcupine checker + KV model the service
  tests use.  The first ``n_multi`` sampled groups are reconstructed
  as MULTI-CLIENT histories — entries round-robined over ``n_clients``
  logical clients with per-client sequential call flooring — so the
  DFS must genuinely arbitrate the interleaving (the histories have
  real linearization choice, not a single admissible order).
  Frontier invariants (commit monotone, commit ≤ ingest) are asserted
  over ALL groups, not just the sample.

The records are the run's own telemetry, so this verifies the actual
timed execution — not a separate small run standing in for it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "concat_records",
    "detect_churn",
    "latency_histogram",
    "prepare_records",
    "verify_sampled_groups",
]


def concat_records(chunks: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Stack per-chunk trace records into one [N_total, G] set."""
    keys = chunks[0].keys()
    return {
        k: np.concatenate([np.asarray(c[k]) for c in chunks], axis=0)
        for k in keys
    }


def prepare_records(
    rec: Dict[str, np.ndarray],
    seed_last: np.ndarray,
    seed_commit: np.ndarray,
) -> Dict[str, object]:
    """One-time i64 conversion + frontier derivation + invariant
    asserts for a trace.  :func:`latency_histogram` and
    :func:`verify_sampled_groups` each need this; callers that run
    both (bench.py) pass the result to BOTH via ``prep=`` so the
    [N, G] copies and the all-groups asserts happen once."""
    arrs = _accept_arrays(rec)
    I, C = _frontiers(rec, seed_last, seed_commit, arrs)
    return {"arrs": arrs, "I": I, "C": C}


def _frontiers(
    rec: Dict[str, np.ndarray],
    seed_last: np.ndarray,
    seed_commit: np.ndarray,
    arrs: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """(I, C): per-tick ingest/commit frontier curves [N, G], with the
    pre-window seeds folded in, plus the invariant asserts."""
    if arrs is not None:
        acc, ing_hi, _ = arrs
    else:
        ing_hi = np.asarray(rec["ing_hi"], np.int64)
        acc = np.asarray(rec["accepted"], np.int64)
    C = np.asarray(rec["commit"], np.int64)
    I = np.maximum.accumulate(np.where(acc > 0, ing_hi, 0), axis=0)
    I = np.maximum(I, np.asarray(seed_last, np.int64)[None, :])
    # Safety invariants over EVERY group of the timed run:
    assert (np.diff(C, axis=0) >= 0).all(), (
        "commit frontier regressed during the bench — committed entries "
        "were lost"
    )
    assert (C[0] >= seed_commit).all(), "commit regressed at chunk boundary"
    assert (C <= I).all(), (
        "commit frontier passed the ingest frontier — entries committed "
        "that were never accepted"
    )
    return I, C


def detect_churn(
    rec: Dict[str, np.ndarray], seed_last: np.ndarray
) -> np.ndarray:
    """bool[G]: groups where some accept window did NOT extend the
    previous ingest frontier — a leader change rebound indices
    mid-window.  Fully vectorized (one forward-fill over the tick
    axis), so the 10k-group bench pays no per-group scan."""
    ing_hi = np.asarray(rec["ing_hi"], np.int64)
    acc = np.asarray(rec["accepted"], np.int64)
    N, G = ing_hi.shape
    rows = np.arange(N, dtype=np.int64)[:, None]
    idx = np.where(acc > 0, rows, np.int64(-1))
    last_idx = np.maximum.accumulate(idx, axis=0)
    prev_idx = np.vstack([np.full((1, G), -1, np.int64), last_idx[:-1]])
    prev_end = np.take_along_axis(ing_hi, np.clip(prev_idx, 0, None), axis=0)
    prev_end = np.where(
        prev_idx >= 0, prev_end, np.asarray(seed_last, np.int64)[None, :]
    )
    churn_tick = (acc > 0) & (ing_hi - acc != prev_end)
    return churn_tick.any(axis=0)


def _accept_arrays(
    rec: Dict[str, np.ndarray],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """ONE-TIME i64 conversion of the accept records.  The per-group
    helpers slice these; converting inside the per-group path would
    memcpy the whole [N, G] record once per group — at 100k groups
    with thousands churned that is terabytes of hidden copying."""
    return (
        np.asarray(rec["accepted"], np.int64),
        np.asarray(rec["ing_hi"], np.int64),
        np.asarray(rec["accept_term"], np.int64),
    )


def _group_accepts(
    arrs: Tuple[np.ndarray, np.ndarray, np.ndarray], g: int
) -> List[Tuple[int, int, int, int]]:
    """Group ``g``'s accept events, in tick order:
    ``(tick, start, end, term)`` — indices ``start+1..end`` were bound
    at ``tick`` with ``term``.  A later event overlapping an earlier
    one is a leader rebind (the later binding supersedes unless the
    ring proves the earlier branch won — see the arbitration in
    :func:`verify_sampled_groups`).  ``arrs`` is
    :func:`_accept_arrays` output."""
    acc_all, ing_all, term_all = arrs
    acc = acc_all[:, g]
    ing = ing_all[:, g]
    terms = term_all[:, g]
    out = []
    for t in np.nonzero(acc > 0)[0]:
        a = int(acc[t])
        end = int(ing[t])
        out.append((int(t), end - a, end, int(terms[t])))
    return out


def _bindings_from_accepts(
    accepts: List[Tuple[int, int, int, int]], origin: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Dense per-index binding arrays over offsets ``1..size`` from
    ``origin`` (= the window-open commit frontier; leader completeness
    guarantees no accept window starts below it): last binding
    tick+term, first binding tick, and a multi-bound flag."""
    size = max((e[2] for e in accepts), default=origin) - origin
    size = max(size, 0)
    bind_tick = np.full(size + 1, -1, np.int64)
    bind_term = np.full(size + 1, -1, np.int64)
    first_tick = np.full(size + 1, -1, np.int64)
    multi = np.zeros(size + 1, bool)
    for t, start, end, term in accepts:
        lo = max(start + 1 - origin, 1)
        hi = end - origin
        if hi < lo:
            continue
        sl = slice(lo, hi + 1)
        prev = bind_tick[sl] >= 0
        multi[sl] |= prev & (bind_term[sl] != term)
        np.copyto(first_tick[sl], t, where=~prev)
        bind_tick[sl] = t
        bind_term[sl] = term
    return bind_tick, bind_term, first_tick, multi


def _churned_group_latencies(
    arrs: Tuple[np.ndarray, np.ndarray, np.ndarray],
    seed_commit: np.ndarray,
    g: int,
    C: np.ndarray,
) -> Tuple[np.ndarray, int, int]:
    """Exact per-entry latencies (ticks) for a churned group: each
    committed index's ingest tick is its LAST binding (the branch that
    won; a superseded binding's entry was truncated and re-accepted).
    Returns (latencies, pre_window_count, rebound_count)."""
    origin = int(seed_commit[g])
    accepts = _group_accepts(arrs, g)
    bind_tick, _, _, multi = _bindings_from_accepts(accepts, origin)
    c_final = int(C[-1, g])
    n_committed = min(c_final - origin, len(bind_tick) - 1)
    if n_committed <= 0:
        return np.zeros(0, np.int64), 0, 0
    off = np.arange(1, n_committed + 1)
    bt = bind_tick[off]
    idxs = origin + off
    t_c = np.searchsorted(C[:, g], idxs, side="left")
    known = bt >= 0
    lat = t_c[known] - bt[known]
    # A non-positive latency is impossible for a correct binding
    # (ingest runs after commit advance within a tick), so it marks a
    # mis-attributed binding — drop it to ``unaccounted`` (via the
    # caller's residual) rather than deflating the histogram.
    lat = lat[lat >= 1]
    pre = int((~known).sum())
    rebound = int(multi[off][known].sum())
    return lat, pre, rebound


def latency_histogram(
    rec: Dict[str, np.ndarray],
    seed_last: np.ndarray,
    seed_commit: np.ndarray,
    max_ticks: int = 256,
    prep: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """Exact ingest→commit latency histogram (ticks) for every entry
    both ingested and committed inside the window; entries ingested
    before the window are counted separately (their ingest tick is
    unknown) and entries still in flight at window end are excluded.
    Calm groups go through the vectorized overlap algebra; churned
    groups (leader rebinds) are measured exactly from their accept
    bindings — faulted runs lose no coverage.  ``prep`` is
    :func:`prepare_records` output, shared with
    :func:`verify_sampled_groups` so the [N, G] conversions and the
    invariant asserts run once per trace."""
    if prep is None:
        prep = prepare_records(rec, seed_last, seed_commit)
    I, C = prep["I"], prep["C"]
    N = I.shape[0]
    seed_last = np.asarray(seed_last, np.int64)
    seed_commit = np.asarray(seed_commit, np.int64)
    churned = detect_churn(rec, seed_last)
    calm = ~churned
    # Churned columns flattened to their seeds contribute zero to the
    # overlap algebra; they are counted exactly below instead.
    Ic = np.where(calm[None, :], I, seed_last[None, :])
    Cc = np.where(calm[None, :], C, seed_commit[None, :])
    Iprev = np.vstack([seed_last[None, :], Ic[:-1]])
    Cprev = np.vstack([seed_commit[None, :], Cc[:-1]])
    committed_calm = int((Cc[-1] - seed_commit).sum())
    pre_window = int(
        np.clip(np.minimum(Cc[-1], seed_last) - seed_commit, 0, None).sum()
    )
    hist: Dict[int, int] = {}
    counted = 0
    target_calm = committed_calm - pre_window
    for k in range(1, min(max_ticks, N) + 1):
        t = np.arange(k, N)
        lo = np.maximum(Iprev[t - k], Cprev[t])
        hi = np.minimum(Ic[t - k], Cc[t])
        n = int(np.clip(hi - lo, 0, None).sum())
        if n:
            hist[k] = n
            counted += n
        if counted >= target_calm:
            break  # every calm in-window entry accounted — stop early
    rebound_entries = 0
    churn_hist: Dict[int, int] = {}
    arrs = prep["arrs"]
    for g in np.nonzero(churned)[0]:
        lat, pre, reb = _churned_group_latencies(arrs, seed_commit, int(g), C)
        pre_window += pre
        rebound_entries += reb
        if lat.size:
            for k, n in zip(*np.unique(lat, return_counts=True)):
                hist[int(k)] = hist.get(int(k), 0) + int(n)
                churn_hist[int(k)] = churn_hist.get(int(k), 0) + int(n)
                counted += int(n)
    committed_total = int((C[-1] - seed_commit).sum())
    # Entries the algebra could not place: latency beyond max_ticks
    # only (churned groups are now measured exactly).  Reported, not
    # asserted — the bench JSON surfaces it so silent coverage loss is
    # impossible.
    unaccounted = committed_total - pre_window - counted
    p50, p99 = _hist_percentiles(hist)
    # Churned-group-only (failover) distribution: the global p99 is
    # diluted by the healthy groups' entries, so the failover tail
    # gets its own first-class percentiles (VERDICT r04 #7).
    fo_p50, fo_p99 = _hist_percentiles(churn_hist)
    return {
        "hist_ticks": hist,
        "entries": counted,
        "pre_window_commits": pre_window,
        "unaccounted": int(unaccounted),
        "churned_groups": int(churned.sum()),
        "rebound_entries": int(rebound_entries),
        "p50_ticks": int(p50),
        "p99_ticks": int(p99),
        "failover_entries": int(sum(churn_hist.values())),
        "failover_p50_ticks": int(fo_p50),
        "failover_p99_ticks": int(fo_p99),
    }


def _hist_percentiles(hist: Dict[int, int]) -> Tuple[int, int]:
    """(p50, p99) of an {latency_ticks: count} histogram; (0, 0) when
    empty."""
    total = sum(hist.values())
    if not total:
        return 0, 0
    cum = 0
    p50 = p99 = max(hist)
    seen50 = False
    for k in sorted(hist):
        cum += hist[k]
        if not seen50 and cum >= 0.50 * total:
            p50 = k
            seen50 = True
        if cum >= 0.99 * total:
            p99 = k
            break
    return p50, p99


def verify_sampled_groups(
    rec: Dict[str, np.ndarray],
    seed_last: np.ndarray,
    seed_commit: np.ndarray,
    sample: List[int],
    final_state,
    cfg,
    budget_s: float = 240.0,
    n_multi: int = 8,
    n_clients: int = 4,
    n_dfs_oracle: int = 8,
    prep: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """Reconstruct each sampled group's operation history from the
    device records, cross-check it against the final device ring, and
    porcupine-check it.  Returns a summary dict for the bench JSON.

    Churned groups are verified, not skipped: rebinds resolve from the
    accept-term records (ring-arbitrated where the ring still covers
    the index; conservatively widened to the earliest binding and
    counted as ``ambiguous_entries`` otherwise).  The first
    ``n_multi`` groups get multi-client histories (``n_clients``
    logical clients, per-client sequential call flooring) so the DFS
    must arbitrate genuinely overlapping operations.

    ``budget_s`` bounds the TOTAL checking wall-clock: groups not
    reached in budget report UNKNOWN (the porcupine timeout
    convention) — an ILLEGAL anywhere still fails the verdict.

    Each group's verdict comes from the EXACT O(n) unique-order
    admissibility scan (:func:`_check_unique_order` — vectorized, so
    128-group sampling costs what 8 used to); the first
    ``n_dfs_oracle`` groups (superset of the multi-client ones) are
    ALSO checked by the full native porcupine DFS as an independent
    oracle, and any disagreement fails loudly."""
    import time as _time

    from ..porcupine.model import CheckResult

    t_end = _time.monotonic() + budget_s

    if prep is None:
        prep = prepare_records(rec, seed_last, seed_commit)
    I, C = prep["I"], prep["C"]
    st = {
        "log_term": np.asarray(final_state.log_term),
        "base": np.asarray(final_state.base),
        "log_len": np.asarray(final_state.log_len),
        "role": np.asarray(final_state.role),
        "alive": np.asarray(final_state.alive),
        "term": np.asarray(final_state.term),
    }
    N = I.shape[0]
    ok = 0
    unknown = 0
    churned_groups = 0
    ambiguous = 0
    arbitrated = 0
    ring_checked = 0
    multi_groups = 0
    max_concurrency = 0
    dfs_checked = 0
    results = []
    arrs = prep["arrs"]
    for j, g in enumerate(sample):
        if _time.monotonic() >= t_end:
            unknown += 1
            results.append((g, "budget-unknown"))
            continue
        origin = int(seed_commit[g])
        accepts = _group_accepts(arrs, g)
        bind_tick, bind_term, first_tick, multi = _bindings_from_accepts(
            accepts, origin
        )
        if multi.any():
            churned_groups += 1

        # Cross-check the reconstruction against the device's own log:
        # every ring-covered bound index must carry a term the records
        # predicted.  Where an index was bound at two terms, the ring
        # is the arbiter — the matching binding's tick becomes the
        # call time (figure-8 revival: the FIRST branch can win).
        p = _leader_slot(st, g)
        base = int(st["base"][g, p])
        ring_hi = base + int(st["log_len"][g, p])
        chosen_tick = bind_tick.copy()
        for idx in range(max(base + 1, origin + 1), ring_hi + 1):
            o = idx - origin
            if o >= len(bind_tick) or bind_tick[o] < 0:
                continue
            got = int(st["log_term"][g, p, idx % cfg.L])
            if got == int(bind_term[o]):
                ring_checked += 1
                continue
            # Scan this index's accept events for a binding at the
            # ring's term (arbitration among >2 bindings).
            cand = [
                t for (t, s_, e_, tm) in accepts
                if s_ < idx <= e_ and tm == got
            ]
            assert cand, (
                f"group {g}: no recorded binding matches device "
                f"ring term {got} at index {idx} (reconstructed term "
                f"{int(bind_term[o])})"
            )
            chosen_tick[o] = cand[-1]
            arbitrated += 1
            ring_checked += 1

        # Committed in-window entries only: pre-window commits have no
        # recorded ingest; entries in flight at window end linearize as
        # "not taken" (absent from the final read) — the
        # partial-history convention.
        commit_final = int(C[-1, g])
        n_comm = min(commit_final - origin, len(bind_tick) - 1)
        offs = np.nonzero(bind_tick[1: max(n_comm, 0) + 1] >= 0)[0] + 1
        idxs = origin + offs
        # Ambiguous: multi-bound, not ring-arbitrable (compacted away)
        # — widen the call interval to the EARLIEST binding (a larger
        # window admits strictly more linearizations: conservative).
        amb = (
            multi[offs]
            & ~((base < idxs) & (idxs <= ring_hi))
            & (chosen_tick[offs] == bind_tick[offs])
        )
        ambiguous += int(amb.sum())
        t_cs = np.searchsorted(C[:, g], idxs, "left")
        calls = np.where(amb, first_tick[offs], chosen_tick[offs]).astype(
            np.float64
        )
        rets = t_cs.astype(np.float64) + 0.5

        # Multi-client reconstruction: round-robin entries over logical
        # clients; per-client sequentiality is enforced by flooring each
        # op's call at its predecessor's return (the floored call is
        # within the true in-flight window, so admissible
        # linearizations only shrink — conservative).  The client count
        # must exceed the largest same-tick commit batch: ops committing
        # the same tick share a return time, so consecutive SAME-client
        # ops must land in different batches for the floor to stay
        # below the op's own return.  Different clients within a batch
        # still fully overlap — the checker arbitrates their order.
        if j < n_multi and len(t_cs):
            _, batch_sizes = np.unique(t_cs, return_counts=True)
            k_eff = max(n_clients, int(batch_sizes.max()) + 1)
            if len(idxs) > k_eff:
                multi_groups += 1
                calls[k_eff:] = np.maximum(
                    calls[k_eff:], rets[:-k_eff] + 0.25
                )
        # Exact O(n) decision (see _check_unique_order: the appended
        # tokens are distinct, so the valid linearization order is
        # UNIQUE and linearizability reduces to a vectorized real-time
        # admissibility scan — same verdict the DFS would return).
        verdict, conc = _check_unique_order(calls, rets)
        # Independent oracle: the first ``n_dfs_oracle`` groups (which
        # include the multi-client reconstructions) ALSO run the full
        # native porcupine DFS; any disagreement is a rig bug and
        # fails loudly.  Failures always get the DFS pass too, so an
        # ILLEGAL verdict carries DFS-confirmed evidence.
        if j < n_dfs_oracle or verdict is not CheckResult.OK:
            remaining = max(t_end - _time.monotonic(), 1.0)
            dfs_verdict, conc = _check_group_history(
                [int(i) for i in idxs], calls, rets, g, N, remaining
            )
            dfs_checked += 1
            assert (
                dfs_verdict is CheckResult.UNKNOWN
                or dfs_verdict is verdict
            ), (
                f"group {g}: fast admissibility check says {verdict} "
                f"but the porcupine DFS says {dfs_verdict} — "
                "verification rig bug"
            )
        max_concurrency = max(max_concurrency, conc)
        results.append((g, verdict.name))
        if verdict == CheckResult.ILLEGAL:
            return {
                "porcupine": "fail",
                "sampled_groups": len(sample),
                "failed_group": g,
                "results": results,
            }
        if verdict == CheckResult.OK:
            ok += 1
        else:
            unknown += 1
    return {
        "porcupine": "ok" if ok else "unknown",
        "sampled_groups": len(sample),
        "groups_ok": ok,
        "groups_unknown": unknown,
        "groups_churned": churned_groups,
        "ambiguous_entries": ambiguous,
        "ring_arbitrated_entries": arbitrated,
        "ring_entries_crosschecked": ring_checked,
        "multi_client_groups": multi_groups,
        "multi_client_clients": n_clients,
        "max_concurrency": max_concurrency,
        "dfs_oracle_groups": dfs_checked,
    }


def _check_unique_order(
    calls: np.ndarray, rets: np.ndarray
) -> Tuple["CheckResult", int]:
    """Exact linearizability decision for the bench's reconstructed
    histories, O(n) vectorized.

    The reconstruction appends DISTINCT tokens (one per log index) and
    closes with a single read of the final value.  Distinct tokens
    mean the final value pins a UNIQUE admissible append order — the
    index order — and the read must follow every append (its observed
    value contains all of them).  A history is therefore linearizable
    iff that one order respects real-time precedence: no op may
    precede (in index order) an op that finished strictly before it
    was called.  Violation test: exists i<j with rets[j] < calls[i]
    — strict, because the entry-order tie-break (calls sort before
    returns at equal times, checker._make_entries) makes touching
    intervals concurrent.  Equivalent to the porcupine DFS verdict on
    the same constructed history (the DFS search over orders collapses
    to this single candidate); ``verify_sampled_groups`` cross-checks
    the equivalence against the real DFS on an oracle subsample every
    run.

    Returns ``(verdict, max_concurrency)`` — concurrency measured the
    same way the DFS path measures it (peak in-flight ops)."""
    from ..porcupine.model import CheckResult

    n = len(calls)
    if n == 0:
        return CheckResult.OK, 0
    prefix_max_call = np.maximum.accumulate(calls)
    viol = bool((rets[1:] < prefix_max_call[:-1]).any())
    times = np.concatenate([calls, rets])
    kinds = np.concatenate(
        [np.zeros(n, np.int8), np.ones(n, np.int8)]
    )
    order = np.lexsort((kinds, times))  # calls first at equal times
    depth = np.cumsum(np.where(kinds[order] == 0, 1, -1))
    conc = int(depth.max(initial=0))
    return (
        CheckResult.ILLEGAL if viol else CheckResult.OK
    ), conc


def _check_group_history(idxs, calls, rets, g, N, timeout_s):
    """Linearizability check of one reconstructed group history.
    ``calls``/``rets`` are per-op float times (already floored /
    widened by the caller).  Returns (verdict, max_concurrency).

    Fast path: marshal the event order STRAIGHT into the native C++
    DFS — the Operation-object layer and its event sort dominated the
    verification wall-clock ~7:1 over the DFS itself.  Falls back to
    the generic checker when the native library is unavailable."""
    from ..porcupine.checker import check_operations
    from ..porcupine.kv import (
        _NATIVE_STEPS_PER_SEC,
        OP_APPEND,
        OP_GET,
        KvInput,
        KvOutput,
        _rc_result,
        kv_model,
    )
    from ..porcupine.model import Operation
    from ..porcupine.native import check_kv_partition_native

    n = len(idxs)
    pieces = [f"[{i}]" for i in idxs]
    value = "".join(pieces)
    # Sort (time, kind, op) events; kind 0 (call) before kind 1
    # (return) at equal times.  A real sort, NOT a two-stream merge:
    # churned reconstructions can have NON-monotone call ticks (a
    # ring-arbitrated or ambiguity-widened index can carry an earlier
    # binding than its predecessor), and a merge that assumes
    # monotonicity would hand the DFS a mis-ordered event sequence.
    times = np.concatenate([np.asarray(calls), np.asarray(rets)])
    ev_kind = np.concatenate([np.zeros(n, np.int8), np.ones(n, np.int8)])
    order = np.lexsort((ev_kind, times))  # calls first at equal times
    events = [
        (int(k) % n, bool(ev_kind[k])) for k in order
    ]
    events.append((n, False))
    events.append((n, True))
    depth = int(
        np.cumsum(np.where(ev_kind[order] == 0, 1, -1)).max(initial=0)
    )
    kinds = [OP_APPEND] * n + [OP_GET]
    values = pieces + [""]
    outputs = [""] * n + [value]
    rc = check_kv_partition_native(
        events, kinds, values, outputs,
        max_steps=max(1, int(timeout_s * _NATIVE_STEPS_PER_SEC)),
        max_wall_s=timeout_s,
    )
    if rc is not None:
        return _rc_result(rc), depth
    # No native toolchain: the generic (Operation-object) path.
    ops = [
        Operation(
            client_id=0,
            input=KvInput(op=OP_APPEND, key=f"g{g}", value=pieces[k]),
            call=float(calls[k]),
            output=KvOutput(),
            ret=float(rets[k]),
        )
        for k in range(n)
    ]
    ops.append(
        Operation(
            client_id=1,
            input=KvInput(op=OP_GET, key=f"g{g}"),
            call=float(N + 1),
            output=KvOutput(value=value),
            ret=float(N + 2),
        )
    )
    return check_operations(kv_model, ops, timeout=timeout_s), depth


def _leader_slot(st, g: int) -> int:
    lead = np.nonzero((st["role"][g] == 2) & st["alive"][g])[0]
    if len(lead) == 0:
        return 0
    return int(lead[np.argmax(st["term"][g][lead])])
