"""Post-hoc verification + measured latency for the headline bench.

Input: the per-tick device records of :func:`core.run_ticks_traced`
(per-group ingest/commit frontiers and accept terms), concatenated
over the timed chunks.  Two consumers:

* :func:`latency_histogram` — the MEASURED per-entry commit-latency
  distribution, in ticks, exact for every entry committed in the
  window.  Overlap algebra on the frontier curves: the entries
  ingested at tick ``s`` and committed at tick ``t`` are the interval
  intersection ``(I[s-1], I[s]] ∩ (C[t-1], C[t]]``, so a handful of
  vectorized passes (one per latency value) count 40M+ entries
  exactly, no per-entry loop.  This replaces the bench's former
  3-ticks-by-assumption p99 model with data.

* :func:`verify_sampled_groups` — the north star's "porcupine-verified
  on sampled shards" applied to the flagship run itself (reference
  pattern: the kvraft harness checks the history of the actual run,
  kvraft/test_test.go:365-381).  Each sampled group's operation
  history is reconstructed from what the device recorded — every
  accepted command becomes an Append whose call time is its ingest
  tick and return time its commit tick — cross-checked against the
  final device ring (the reconstruction must agree with the log's
  terms, entry for entry), then checked with the same porcupine
  checker + KV model the service tests use.  Frontier invariants
  (commit monotone, commit ≤ ingest) are asserted over ALL groups,
  not just the sample.

The records are the run's own telemetry, so this verifies the actual
timed execution — not a separate small run standing in for it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["concat_records", "latency_histogram", "verify_sampled_groups"]


def concat_records(chunks: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Stack per-chunk trace records into one [N_total, G] set."""
    keys = chunks[0].keys()
    return {
        k: np.concatenate([np.asarray(c[k]) for c in chunks], axis=0)
        for k in keys
    }


def _frontiers(
    rec: Dict[str, np.ndarray], seed_last: np.ndarray, seed_commit: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """(I, C): per-tick ingest/commit frontier curves [N, G], with the
    pre-window seeds folded in, plus the invariant asserts."""
    ing_hi = np.asarray(rec["ing_hi"], np.int64)
    acc = np.asarray(rec["accepted"], np.int64)
    C = np.asarray(rec["commit"], np.int64)
    I = np.maximum.accumulate(np.where(acc > 0, ing_hi, 0), axis=0)
    I = np.maximum(I, np.asarray(seed_last, np.int64)[None, :])
    # Safety invariants over EVERY group of the timed run:
    assert (np.diff(C, axis=0) >= 0).all(), (
        "commit frontier regressed during the bench — committed entries "
        "were lost"
    )
    assert (C[0] >= seed_commit).all(), "commit regressed at chunk boundary"
    assert (C <= I).all(), (
        "commit frontier passed the ingest frontier — entries committed "
        "that were never accepted"
    )
    return I, C


def latency_histogram(
    rec: Dict[str, np.ndarray],
    seed_last: np.ndarray,
    seed_commit: np.ndarray,
    max_ticks: int = 64,
) -> Dict[str, object]:
    """Exact ingest→commit latency histogram (ticks) for every entry
    both ingested and committed inside the window; entries ingested
    before the window are counted separately (their ingest tick is
    unknown) and entries still in flight at window end are excluded."""
    I, C = _frontiers(rec, seed_last, seed_commit)
    N = I.shape[0]
    seed_last = np.asarray(seed_last, np.int64)
    seed_commit = np.asarray(seed_commit, np.int64)
    Iprev = np.vstack([seed_last[None, :], I[:-1]])
    Cprev = np.vstack([seed_commit[None, :], C[:-1]])
    hist: Dict[int, int] = {}
    for k in range(1, min(max_ticks, N) + 1):
        t = np.arange(k, N)
        lo = np.maximum(Iprev[t - k], Cprev[t])
        hi = np.minimum(I[t - k], C[t])
        n = int(np.clip(hi - lo, 0, None).sum())
        if n:
            hist[k] = n
    committed_total = int((C[-1] - seed_commit).sum())
    pre_window = int(
        np.clip(np.minimum(C[-1], seed_last) - seed_commit, 0, None).sum()
    )
    counted = sum(hist.values())
    # Entries the overlap algebra could not place: latency beyond
    # max_ticks, or groups whose leader changed mid-window (a rebind
    # makes the running-max ingest frontier mislabel ticks).  Reported,
    # not asserted — one churned group must not abort the whole bench
    # after the timed chunks already ran (the sampled-group verifier
    # reports churn explicitly).
    unaccounted = committed_total - pre_window - counted
    total = max(counted, 1)
    cum = 0
    p50 = p99 = max(hist) if hist else 0
    for k in sorted(hist):
        cum += hist[k]
        if cum >= 0.50 * total and p50 == max(hist):
            p50 = k
        if cum >= 0.99 * total:
            p99 = k
            break
    return {
        "hist_ticks": hist,
        "entries": counted,
        "pre_window_commits": pre_window,
        "unaccounted": int(unaccounted),
        "p50_ticks": int(p50),
        "p99_ticks": int(p99),
    }


def verify_sampled_groups(
    rec: Dict[str, np.ndarray],
    seed_last: np.ndarray,
    seed_commit: np.ndarray,
    sample: List[int],
    final_state,
    cfg,
    budget_s: float = 240.0,
) -> Dict[str, object]:
    """Reconstruct each sampled group's operation history from the
    device records, cross-check it against the final device ring, and
    porcupine-check it.  Returns a summary dict for the bench JSON.

    ``budget_s`` bounds the TOTAL checking wall-clock: groups not
    reached in budget report UNKNOWN (the porcupine timeout
    convention) — an ILLEGAL anywhere still fails the verdict."""
    import time as _time

    from ..porcupine.model import CheckResult

    t_end = _time.monotonic() + budget_s

    I, C = _frontiers(rec, seed_last, seed_commit)
    ing_hi = np.asarray(rec["ing_hi"], np.int64)
    acc = np.asarray(rec["accepted"], np.int64)
    terms = np.asarray(rec["accept_term"], np.int64)
    st = {
        "log_term": np.asarray(final_state.log_term),
        "base": np.asarray(final_state.base),
        "log_len": np.asarray(final_state.log_len),
        "role": np.asarray(final_state.role),
        "alive": np.asarray(final_state.alive),
        "term": np.asarray(final_state.term),
    }
    N = I.shape[0]
    ok = 0
    unknown = 0
    skipped_churn = 0
    ring_checked = 0
    results = []
    for g in sample:
        if _time.monotonic() >= t_end:
            unknown += 1
            results.append((g, "budget-unknown"))
            continue
        # Per-index (ingest tick, term) assignments from the accept
        # records.  A tick whose accept window does not extend the
        # previous frontier means a leader change rebound indices —
        # possible under faults, not expected in the fault-free bench;
        # such a group is reported, not silently mis-reconstructed.
        entries: Dict[int, Tuple[int, int]] = {}
        last = int(seed_last[g])
        churn = False
        for t in range(N):
            a = int(acc[t, g])
            if a == 0:
                continue
            start = int(ing_hi[t, g]) - a
            if start != last:
                churn = True
                break
            for off in range(a):
                entries[start + 1 + off] = (t, int(terms[t, g]))
            last = start + a
        if churn:
            skipped_churn += 1
            results.append((g, "churn-skip"))
            continue

        # Cross-check the reconstruction against the device's own log:
        # the final ring's window must carry exactly the terms the
        # records predicted, entry for entry.
        p = _leader_slot(st, g)
        base = int(st["base"][g, p])
        lo = max(base + 1, int(seed_last[g]) + 1)
        hi = base + int(st["log_len"][g, p])
        for idx in range(lo, hi + 1):
            if idx in entries:
                got = int(st["log_term"][g, p, idx % cfg.L])
                want = entries[idx][1]
                assert got == want, (
                    f"group {g}: reconstructed term {want} != device "
                    f"ring term {got} at index {idx}"
                )
                ring_checked += 1

        # Build the porcupine history: window-committed appends with
        # their real (ingest, commit) tick intervals + one final read
        # of the window's concatenation.  Entries still in flight at
        # window end linearize as "not taken" (excluded, and absent
        # from the read's value) — the partial-history convention.
        commit_final = int(C[-1, g])
        idxs = [i for i in sorted(entries) if i <= commit_final]
        t_ins = [entries[i][0] for i in idxs]
        t_cs = np.searchsorted(C[:, g], np.asarray(idxs), side="left")
        remaining = max(t_end - _time.monotonic(), 1.0)
        verdict = _check_group_history(idxs, t_ins, t_cs, g, N, remaining)
        results.append((g, verdict.name))
        if verdict == CheckResult.ILLEGAL:
            return {
                "porcupine": "fail",
                "sampled_groups": len(sample),
                "failed_group": g,
                "results": results,
            }
        if verdict == CheckResult.OK:
            ok += 1
        else:
            unknown += 1
    return {
        "porcupine": "ok" if ok else "unknown",
        "sampled_groups": len(sample),
        "groups_ok": ok,
        "groups_unknown": unknown,
        "groups_churn_skipped": skipped_churn,
        "ring_entries_crosschecked": ring_checked,
    }


def _check_group_history(idxs, t_ins, t_cs, g, N, timeout_s):
    """Linearizability check of one reconstructed group history.

    Fast path: marshal the arrays STRAIGHT into the native C++ DFS —
    the events are already sorted (ingest and commit frontiers are
    both monotone in idx, and call events precede returns via the kind
    key), so the Operation-object layer and its event sort (which
    dominated the bench's verification wall-clock ~7:1 over the DFS
    itself) are skipped.  Falls back to the generic checker when the
    native library is unavailable."""
    from ..porcupine.checker import check_operations
    from ..porcupine.kv import (
        _NATIVE_STEPS_PER_SEC,
        OP_APPEND,
        OP_GET,
        KvInput,
        KvOutput,
        _rc_result,
        kv_model,
    )
    from ..porcupine.model import Operation
    from ..porcupine.native import check_kv_partition_native

    n = len(idxs)
    pieces = [f"[{i}]" for i in idxs]
    value = "".join(pieces)
    # Interleave (time, kind, op) in sorted order by merging the two
    # already-sorted streams: calls at t_in (kind 0), returns at
    # t_c + 0.5 (kind 1).  The final get's events land after all.
    events = []
    a = b = 0
    while a < n or b < n:
        if a < n and (b >= n or t_ins[a] <= t_cs[b] + 0.5):
            events.append((a, False))
            a += 1
        else:
            events.append((b, True))
            b += 1
    events.append((n, False))
    events.append((n, True))
    kinds = [OP_APPEND] * n + [OP_GET]
    values = pieces + [""]
    outputs = [""] * n + [value]
    rc = check_kv_partition_native(
        events, kinds, values, outputs,
        max_steps=max(1, int(timeout_s * _NATIVE_STEPS_PER_SEC)),
        max_wall_s=timeout_s,
    )
    if rc is not None:
        return _rc_result(rc)
    # No native toolchain: the generic (Operation-object) path.
    ops = [
        Operation(
            client_id=0,
            input=KvInput(op=OP_APPEND, key=f"g{g}", value=pieces[k]),
            call=float(t_ins[k]),
            output=KvOutput(),
            ret=float(t_cs[k]) + 0.5,
        )
        for k in range(n)
    ]
    ops.append(
        Operation(
            client_id=1,
            input=KvInput(op=OP_GET, key=f"g{g}"),
            call=float(N + 1),
            output=KvOutput(value=value),
            ret=float(N + 2),
        )
    )
    return check_operations(kv_model, ops, timeout=timeout_s)


def _leader_slot(st, g: int) -> int:
    lead = np.nonzero((st["role"][g] == 2) & st["alive"][g])[0]
    if len(lead) == 0:
        return 0
    return int(lead[np.argmax(st["term"][g][lead])])
