"""Raft safety-invariant monitor for the batched engine.

The reference's test strategy checks safety with invariant appliers
(cross-server commit consistency, reference: raft/config.go:144-186) and
post-hoc linearizability.  The batched engine admits something stronger:
because the entire cluster state is two host readbacks away, a monitor
can assert the four Raft safety properties *on every tick*, under
arbitrary fault schedules:

* **Election safety** — at most one leader per (group, term), ever
  (reference guarantee exercised by raft/test_test.go:55-125).
* **Committed-term durability** (Leader Completeness + State Machine
  Safety) — the first time any replica commits index *i*, the term of
  *i* is recorded; no replica may ever commit a different term at *i*,
  in this or any future term (reference: raft/test_test.go:817-956,
  the Figure-8 suite).
* **Log Matching** — if two replicas hold the same term at index *i*,
  their logs are identical at every index ≤ *i* both hold
  (Raft §5.3; the reference checks the committed shadow of this at
  raft/config.go:144-163).
* **Monotonicity** — ``term`` never decreases (persistent state);
  ``commit`` never decreases while a replica stays up (it may lawfully
  rewind to the snapshot floor across a crash/restart, which the
  monitor is told about via :meth:`note_restart`).

Used by the fuzz suite (tests/test_engine_fuzz.py): a random fault
script (crashes, restarts, partitions, message loss, Start() load) runs
against the engine while ``observe()`` fires every tick.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .core import LEADER
from .host import EngineDriver

__all__ = ["InvariantMonitor"]


class InvariantMonitor:
    """Cross-tick safety monitor over an :class:`EngineDriver`.

    Call :meth:`observe` after every tick (or batch of ticks — the
    invariants are stable under sampling, but per-tick catches
    violations at their first observable state).  Raises
    ``AssertionError`` with a precise diagnosis on any violation.
    """

    def __init__(self, driver: EngineDriver) -> None:
        self.d = driver
        G, P = driver.cfg.G, driver.cfg.P
        # (group, term) -> leader replica id.
        self.leader_of_term: Dict[Tuple[int, int], int] = {}
        # (group, index) -> term committed there (write-once).
        self.committed_term: Dict[Tuple[int, int], int] = {}
        self.prev_term = np.zeros((G, P), np.int64)
        self.prev_commit = np.zeros((G, P), np.int64)
        # Replicas restarted since the last observe(), mapped to their
        # snapshot floor at restart time: commit may rewind, but never
        # below that floor.
        self._restarted: Dict[Tuple[int, int], int] = {}

    def note_restart(self, g: int, p: int) -> None:
        self._restarted[(g, p)] = int(self.d.state.base[g, p])

    def prune_below_snapshot_floor(self) -> int:
        """Drop committed-term records below each group's cluster-wide
        snapshot floor (min ``base`` over replicas): no replica still
        holds those ring slots, so the records can never be re-checked.
        Bounds memory for soak-length runs; returns entries dropped."""
        base = np.asarray(self.d.state.base)
        floor = base.min(axis=1)  # [G]
        before = len(self.committed_term)
        self.committed_term = {
            (g, i): t
            for (g, i), t in self.committed_term.items()
            if i > floor[g]
        }
        return before - len(self.committed_term)

    # -- the four checks ---------------------------------------------------

    def observe(self, st=None) -> None:
        """``st``: optionally pass a pre-fetched :meth:`EngineDriver.
        np_state` dict to avoid a second device→host sync when the
        caller already read the state this tick."""
        if st is None:
            st = self.d.np_state()
        cfg = self.d.cfg
        term = st["term"].astype(np.int64)
        commit = st["commit"].astype(np.int64)
        self._check_election_safety(st)
        self._check_monotonicity(term, commit)
        views = [
            [self.d.log_terms_of(g, p, st) for p in range(cfg.P)]
            for g in range(cfg.G)
        ]
        self._check_committed_terms(st, views)
        self._check_log_matching(st, views)
        self.prev_term = term
        self.prev_commit = commit
        self._restarted.clear()

    def _check_election_safety(self, st) -> None:
        lead = (st["role"] == LEADER) & st["alive"]
        for g, p in zip(*np.nonzero(lead)):
            t = int(st["term"][g, p])
            prev = self.leader_of_term.setdefault((int(g), t), int(p))
            assert prev == int(p), (
                f"election safety: group {g} term {t} has two leaders "
                f"{prev} and {p}"
            )

    def _check_monotonicity(self, term, commit) -> None:
        bad_t = term < self.prev_term
        assert not bad_t.any(), (
            f"term rewound at {np.argwhere(bad_t).tolist()} "
            f"({self.prev_term[bad_t]} -> {term[bad_t]})"
        )
        bad_c = commit < self.prev_commit
        for g, p in np.argwhere(bad_c):
            floor = self._restarted.get((int(g), int(p)))
            assert floor is not None, (
                f"commit rewound at ({g},{p}) without a restart: "
                f"{self.prev_commit[g, p]} -> {commit[g, p]}"
            )
            assert commit[g, p] >= floor, (
                f"restart rewound commit at ({g},{p}) below its snapshot "
                f"floor {floor}: -> {commit[g, p]}"
            )

    def _check_committed_terms(self, st, views) -> None:
        cfg = self.d.cfg
        for g in range(cfg.G):
            for p in range(cfg.P):
                c = int(st["commit"][g, p])
                base = int(st["base"][g, p])
                v = views[g][p]
                # A replica's own window always covers (base, last];
                # commit past the log end is never legal.
                assert c <= base + int(st["log_len"][g, p]), (
                    f"commit past log end at ({g},{p}): commit {c}, "
                    f"window (base {base}, len {int(st['log_len'][g, p])})"
                )
                for i in range(base + 1, c + 1):
                    t = v[i]
                    rec = self.committed_term.setdefault((g, i), t)
                    assert rec == t, (
                        f"state-machine safety: group {g} index {i} "
                        f"committed term {rec}, but replica {p} has "
                        f"committed term {t}"
                    )

    def _check_log_matching(self, st, views) -> None:
        cfg = self.d.cfg
        for g in range(cfg.G):
            for a in range(cfg.P):
                for b in range(a + 1, cfg.P):
                    va, vb = views[g][a], views[g][b]
                    shared = sorted(set(va) & set(vb), reverse=True)
                    # Highest shared index with equal terms pins the
                    # whole shared prefix below it (Raft §5.3).
                    for i in shared:
                        if va[i] == vb[i]:
                            for j in shared:
                                if j <= i:
                                    assert va[j] == vb[j], (
                                        f"log matching: group {g} "
                                        f"replicas {a}/{b} agree at "
                                        f"{i} (term {va[i]}) but differ "
                                        f"at {j}: {va[j]} vs {vb[j]}"
                                    )
                            break
