"""Pallas TPU kernels for the consensus hot ops.

The north-star kernel (BASELINE.json): the leader-side quorum commit
advance — per (group, leader): the quorum-th largest ``match_index``
with the current-term guard (reference: raft/raft_append_entry.go:
89-105) — plus the RequestVote tally (reference: raft/raft_election.go:
27-49).

Layout choice: the *groups* axis rides the TPU lane dimension (last,
128-wide); the peer axes (P = 3..7) are tiny and unroll into the
sublane/register file.  So kernels take ``[..., G]``-transposed views
and the grid tiles G.  With P this small a sort is wasted work — the
quorum index is computed by the O(P²) counting identity

    q = max_j ( match[j]  if  |{k : match[k] >= match[j]}| >= quorum )

which is pure VPU element-wise + tiny reductions, and the term guard's
ring gather becomes a one-hot multiply-sum over the L axis (no dynamic
gather needed).

On non-TPU backends the kernels run in Pallas interpret mode; parity
tests pin them against the jnp reference implementation in
``core.tick_impl``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["quorum_commit_pallas", "vote_tally_pallas"]


def _commit_kernel(
    match_ref,  # i32[P, P, bG]   eff_match (diag already = own last)
    term_ref,  # i32[P, bG]      current term per replica
    commit_ref,  # i32[P, bG]
    base_ref,  # i32[P, bG]
    base_term_ref,  # i32[P, bG]
    log_ref,  # i32[P, L, bG]   log ring (terms)
    lead_ref,  # i32[P, bG]      1 where (leader & alive)
    out_ref,  # i32[P, bG]      new commit
    *,
    quorum: int,
    L: int,
):
    match = match_ref[...]  # [P, P, bG]
    # Counting-based k-th largest: for each candidate entry j, how many
    # entries in the row are >= it?
    ge = (match[:, :, None, :] >= match[:, None, :, :]).astype(jnp.int32)
    # ge[p, k, j, g] = match[p,k] >= match[p,j]; count over k.
    cnt = ge.sum(axis=1)  # [P, P(bj), bG]
    eligible = cnt >= quorum
    q = jnp.max(jnp.where(eligible, match, 0), axis=1)  # [P, bG]

    # Term of absolute index q: one-hot over the ring slot (q % L), with
    # the dummy head (q == base) supplied by base_term.
    slot = jnp.remainder(q, L)  # [P, bG]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, L, 1), 1)  # [1, L, 1]
    onehot = (slot[:, None, :] == lanes).astype(jnp.int32)  # [P, L, bG]
    ring_term = (log_ref[...] * onehot).sum(axis=1)  # [P, bG]
    q_term = jnp.where(q == base_ref[...], base_term_ref[...], ring_term)

    commit = commit_ref[...]
    ok = (
        (lead_ref[...] == 1)
        & (q_term == term_ref[...])
        & (q > commit)
    )
    out_ref[...] = jnp.where(ok, q, commit)


@functools.partial(jax.jit, static_argnames=("quorum", "interpret", "block_g"))
def quorum_commit_pallas(
    eff_match: jnp.ndarray,  # i32[G, P, P]
    term: jnp.ndarray,  # i32[G, P]
    commit: jnp.ndarray,  # i32[G, P]
    base: jnp.ndarray,  # i32[G, P]
    base_term: jnp.ndarray,  # i32[G, P]
    log_term: jnp.ndarray,  # i32[G, P, L]
    is_leader: jnp.ndarray,  # bool[G, P]
    quorum: int,
    interpret: bool = False,
    block_g: int = 512,
) -> jnp.ndarray:
    """New commit index per replica — the batched north-star op."""
    G, P, _ = eff_match.shape
    L = log_term.shape[-1]
    bG = min(block_g, G)
    n_blocks = -(-G // bG)
    padded = n_blocks * bG

    def pad(x):
        if padded == G:
            return x
        width = [(0, padded - G)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, width)

    # Transpose to groups-last so G rides the lane dimension.
    match_t = jnp.transpose(pad(eff_match), (1, 2, 0))  # [P, P, G']
    term_t = jnp.transpose(pad(term), (1, 0))
    commit_t = jnp.transpose(pad(commit), (1, 0))
    base_t = jnp.transpose(pad(base), (1, 0))
    bterm_t = jnp.transpose(pad(base_term), (1, 0))
    log_t = jnp.transpose(pad(log_term), (1, 2, 0))  # [P, L, G']
    lead_t = jnp.transpose(pad(is_leader.astype(jnp.int32)), (1, 0))

    grid = (n_blocks,)
    gspec2 = pl.BlockSpec((P, bG), lambda i: (0, i))
    out = pl.pallas_call(
        functools.partial(_commit_kernel, quorum=quorum, L=L),
        out_shape=jax.ShapeDtypeStruct((P, padded), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((P, P, bG), lambda i: (0, 0, i)),
            gspec2,
            gspec2,
            gspec2,
            gspec2,
            pl.BlockSpec((P, L, bG), lambda i: (0, 0, i)),
            gspec2,
        ],
        out_specs=gspec2,
        interpret=interpret,
    )(match_t, term_t, commit_t, base_t, bterm_t, log_t, lead_t)
    return jnp.transpose(out, (1, 0))[:G]  # back to [G, P]


def _tally_kernel(votes_ref, role_ref, alive_ref, out_ref, *, quorum: int):
    # votes[P, P, bG]: candidate p's votes from each peer.
    n = votes_ref[...].astype(jnp.int32).sum(axis=1)  # [P, bG]
    out_ref[...] = (
        (role_ref[...] == 1) & (alive_ref[...] == 1) & (n >= quorum)
    ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("quorum", "interpret", "block_g"))
def vote_tally_pallas(
    votes: jnp.ndarray,  # bool[G, P, P]
    role: jnp.ndarray,  # i32[G, P]
    alive: jnp.ndarray,  # bool[G, P]
    quorum: int,
    interpret: bool = False,
    block_g: int = 512,
) -> jnp.ndarray:
    """bool[G, P]: which candidates just won their election."""
    G, P, _ = votes.shape
    bG = min(block_g, G)
    n_blocks = -(-G // bG)
    padded = n_blocks * bG

    def pad(x):
        if padded == G:
            return x
        width = [(0, padded - G)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, width)

    votes_t = jnp.transpose(pad(votes).astype(jnp.int32), (1, 2, 0))
    role_t = jnp.transpose(pad(role), (1, 0))
    alive_t = jnp.transpose(pad(alive).astype(jnp.int32), (1, 0))
    gspec2 = pl.BlockSpec((P, bG), lambda i: (0, i))
    out = pl.pallas_call(
        functools.partial(_tally_kernel, quorum=quorum),
        out_shape=jax.ShapeDtypeStruct((P, padded), jnp.int32),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((P, P, bG), lambda i: (0, 0, i)),
            gspec2,
            gspec2,
        ],
        out_specs=gspec2,
        interpret=interpret,
    )(votes_t, role_t, alive_t)
    return jnp.transpose(out, (1, 0))[:G].astype(bool)
