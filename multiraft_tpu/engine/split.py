"""Cross-process replica groups — a group's P peers split over several
chip-owning engine processes.

Everywhere else in the engine stack, one process hosts *all* P peers of
its groups: the fleet partitions by gid, the mesh shards groups over
chips, and consensus stays inside one tensor.  That makes each process a
whole-group failure domain — losing it loses every replica of its
groups at once, and durability degenerates to checkpoint+WAL on one
disk.  This module restores the reference's per-server failure
independence (reference: labrpc/labrpc.go:316-364 per-edge enables,
raft/config.go:113-142 per-server crash) the TPU-native way:

* Each participating process runs the SAME batched engine shapes
  ``[G, P]`` for the split groups, but *owns* only a subset of the P
  peer slots per group.  Non-owned ("remote") slots are masked
  ``alive=False`` locally: they never tick, never send, and deliveries
  to them are masked — the real peer lives in another process.
* After every device tick, the boundary mailbox lanes
  ``[g, src∈owned, dst∈remote]`` are pulled to host as a **slab** and
  shipped to the owning peer process over the fleet transport; incoming
  slabs are OR-injected into the local inbox at
  ``[g, src∈remote, dst∈owned]`` before the next tick.  Consensus
  within each chip stays zero-collective; the slab exchange is plain
  host-side RPC (SURVEY §2.2's "node↔node over DCN/gRPC").
* Append lanes carry their **entry payloads** (the host-side commands
  the device only orders as (term, index)) and, for InstallSnapshot
  fast-forwards, the service's per-group state blob — so every process
  hosting a replica materializes the full applied state machine, and a
  client can fail over to whichever process holds the new leader.

Payload identity is **(group, index, term)** — the same identity the
device log orders.  Terms at one index are NOT monotone across rebinds
(Raft figure-8: an uncommitted higher-term binding can be replaced by
a committed lower-term entry), so payload candidates are kept per term
and the committed entry's term — read from the device ring at apply
time, the log being the single source of truth — picks the command to
apply.  To keep that read always possible, the peering clamps device
``applied`` down to the host's applied frontier for split groups, so
ring compaction never passes an index the host has yet to apply.

Failure model: a slab that never arrives is a dropped message — Raft
retries by design (heartbeat repair, conflict backoff), so a slow or
dead peer only adds latency, never corrupts.  Losing a process loses
exactly its owned slots; if the surviving processes hold a quorum of a
group, the group keeps electing and committing, and every acknowledged
write is intact from replication alone — no WAL replay.

Crash model: a killed process must NOT be restarted with FRESH state
under the same peer identity — a Raft peer that forgets its term/vote
can double-vote (the reference always carries the Persister across
restarts, raft/config.go:113-142).  Two supported modes:

* non-durable — a lost process stays lost; the surviving quorum keeps
  the group available with every acked write intact;
* durable (``distributed/split_server.SplitPersistence``) — each
  process fsyncs its owned slots' term/vote/log BEFORE each pump's
  slabs leave, so kill -9 + restart on the same data_dir REJOINS
  safely (the Persister-carryover crash model, at engine-slice
  granularity).

This is the fault-tolerance serving path, not the 100k-group bench
path: slab extraction costs one small host readback per tick, so split
groups are meant for the distributed deployment shapes (G up to a few
hundred), with throughput-critical groups staying whole-chip.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .host import EngineDriver
from .kv import BatchedKV, KVOp, Ticket, apply_kv_op
from ..porcupine.kv import OP_APPEND, OP_GET, OP_PUT

__all__ = ["SplitSpec", "SplitPeering", "SplitFrontierMixin", "SplitKV"]

_PREFIXES = ("vr_", "vp_", "ar_", "ap_")


@dataclasses.dataclass(frozen=True)
class SplitSpec:
    """Placement of the split groups' peer slots over processes.

    ``owners[g]`` is a length-P list: ``owners[g][p]`` = process index
    that owns peer slot ``p`` of group ``g``.  Groups absent from
    ``owners`` are wholly local to every process that hosts them (the
    ordinary engine deployment).  All participating processes must be
    constructed with the *same* spec (it is part of cluster config,
    like the reference harness's server lists)."""

    me: int
    owners: Dict[int, List[int]]

    def owned_slots(self, g: int) -> List[int]:
        return [p for p, o in enumerate(self.owners[g]) if o == self.me]

    def remote_slots(self, g: int) -> List[int]:
        return [p for p, o in enumerate(self.owners[g]) if o != self.me]

    def peer_procs(self) -> List[int]:
        return sorted(
            {o for owner in self.owners.values() for o in owner}
            - {self.me}
        )


class SplitPeering:
    """Owns the slab exchange for one process's :class:`EngineDriver`.

    Construction masks the remote slots dead; :meth:`extract` builds
    one slab per peer process from the just-produced outbox (call after
    every ``pump``/``step``); :meth:`inject` merges a received slab
    into the inbox (call from the transport handler, same thread as the
    tick loop).  Payload candidate storage, term arbitration, and
    retention GC live here too.
    """

    GC_EVERY = 64  # ticks between payload-retention GC sweeps

    def __init__(self, driver: EngineDriver, service: "SplitKV",
                 spec: SplitSpec) -> None:
        P = driver.cfg.P
        for g, owner in spec.owners.items():
            if len(owner) != P:
                raise ValueError(
                    f"SplitSpec.owners[{g}] must list {P} slots"
                )
            if not 0 <= g < driver.cfg.G:
                raise ValueError(f"split group {g} outside engine G")
        if not driver.cfg.host_paced_compaction:
            raise ValueError(
                "split groups need EngineConfig(host_paced_compaction="
                "True): term arbitration reads committed entries' terms "
                "from the ring, so compaction must not outrun the host "
                "apply frontier"
            )
        self.driver = driver
        self.service = service
        self.spec = spec
        self.split_gs = sorted(spec.owners)
        self._owned = {g: spec.owned_slots(g) for g in self.split_gs}
        self._remote = {g: spec.remote_slots(g) for g in self.split_gs}
        # Resends need payloads after first apply: keep them until the
        # ring floor passes (entries below base travel as snapshots).
        service.retain_payloads = True
        service.peering = self
        if hasattr(service, "_attach_peering"):
            service._attach_peering(self)  # per-process identity setup
        self._gc_countdown = self.GC_EVERY
        # (g, idx) -> {term: payload}.  The DEVICE log is the sole
        # arbiter of which command occupies an index: candidates from
        # local ingest and from peer slabs are kept per term, and the
        # committed entry's ring term picks the one to apply
        # (see resolve()).  driver.payloads keeps a representative so
        # the base FrontierService machinery (orphan sweeps, eviction)
        # still sees bindings.
        self._cands: Dict[Tuple[int, int], Dict[int, Any]] = {}
        driver.on_payload_bound = self._on_local_bound
        # Persistence hook (distributed/split_server.SplitPersistence):
        # fired for every NEW candidate — (g, idx, term, payload) —
        # so the WAL can re-materialize commands on restart.
        self.on_candidate = None
        # Extra GC floor per group (the persistence snapshot frontier):
        # candidates above the ring floor may still be needed to replay
        # service state from the last snapshot.
        self.gc_floor: Dict[int, int] = {}
        # Mask remote slots dead BEFORE any tick: they belong to peers.
        alive = np.asarray(driver.state.alive).copy()
        for g in self.split_gs:
            for p in self._remote[g]:
                alive[g, p] = False
        # jnp.array(..., copy=True), NOT jnp.asarray: the CPU backend
        # may zero-copy the numpy array, and the tick DONATES state —
        # XLA would then recycle memory it does not own, and the alive
        # mask reads back as garbage a few ticks later (observed: both
        # owned columns flipping dead, so the group never elects;
        # mirror of EngineDriver.restore, host.py).
        driver.state = driver.state._replace(
            alive=jnp.array(alive, copy=True)
        )
        self._g_index = np.asarray(self.split_gs, np.int32)
        self._g_pos = {g: i for i, g in enumerate(self.split_gs)}
        # Per-pump cached device view for term arbitration (ring/base of
        # the split groups); refreshed lazily per tick on first use.
        self._view = None
        self._view_tick = -1
        # The per-tick slab hot path is DISPATCH-bound, not size-bound:
        # naively each extract costs one device op per mailbox field
        # (~23) and each inject ~20 ``.at[].set`` dispatches.  Fuse
        # both: extract slices every field in ONE compiled call, and
        # injected lanes STAGE into host overlay buffers that merge
        # into the device inbox in one compiled call per pump
        # (flush_staged, called by SplitFrontierMixin.pump before the
        # tick).  Measured: 16.5× → ~2× overhead vs the whole-chip
        # pump at the benchmark shape (benchmarks/split_bench.py).
        g_index = self._g_index
        self._slice_fn = jax.jit(
            lambda mb: jax.tree.map(lambda a: a[g_index], mb)
        )
        S, P, E = len(self.split_gs), driver.cfg.P, driver.cfg.E
        from .core import Mailbox as _MB

        self._stage_vals = {}
        if S:
            for f in _MB._fields:
                a = getattr(driver.inbox, f)
                shape = (S, P, P, E) if a.ndim == 4 else (S, P, P)
                self._stage_vals[f] = np.zeros(shape, a.dtype)
        self._stage_mask = {p: np.zeros((max(S, 1), P, P), bool)
                            for p in _PREFIXES}
        self._stage_dirty = False

        def _merge(mb, masks, vals):
            new = {}
            for prefix in _PREFIXES:
                m = masks[prefix]
                for f in _MB._fields:
                    if not f.startswith(prefix):
                        continue
                    a = new.get(f, getattr(mb, f))
                    sub = a[g_index]
                    mm = m[..., None] if sub.ndim == 4 else m
                    a = a.at[g_index].set(jnp.where(mm, vals[f], sub))
                    new[f] = a
            return mb._replace(**new)

        self._merge_fn = jax.jit(_merge, donate_argnums=0)

    # -- payload candidates ------------------------------------------------

    def _on_local_bound(self, g: int, idx: int, term: int) -> None:
        if g in self.spec.owners:
            payload = self.driver.payloads[(g, idx)]
            cands = self._cands.setdefault((g, idx), {})
            if term not in cands and self.on_candidate is not None:
                self.on_candidate(g, idx, term, payload)
            cands[term] = payload

    def _ring_view(self):
        """Host copy of (log_term, base, base_term, commit) for the
        split groups, at most once per tick."""
        if self._view_tick != self.driver.tick or self._view is None:
            st = self.driver.state
            self._view = jax.device_get({
                "log_term": st.log_term[self._g_index],
                "base": st.base[self._g_index],
                "base_term": st.base_term[self._g_index],
                "commit": st.commit[self._g_index],
            })
            self._view_tick = self.driver.tick
        return self._view

    def committed_term(self, g: int, idx: int) -> Optional[int]:
        """Term of committed entry ``idx`` in group ``g``, read from an
        owned replica's ring.  The applied-frontier clamp in
        :meth:`SplitKV.pump` guarantees compaction never passes an
        unapplied index, so the ring always covers what apply needs."""
        v = self._ring_view()
        gi = self._g_pos[g]
        L = self.driver.cfg.L
        for p in self._owned[g]:
            if int(v["commit"][gi, p]) >= idx:
                if idx == int(v["base"][gi, p]):
                    return int(v["base_term"][gi, p])
                if idx > int(v["base"][gi, p]):
                    return int(v["log_term"][gi, p, idx % L])
        return None  # not committed at any owned replica yet

    def resolve(self, g: int, idx: int, fallback: Any) -> Any:
        """Payload to apply for committed ``(g, idx)`` — see
        :meth:`resolve_with_term`."""
        return self.resolve_with_term(g, idx, fallback)[0]

    def resolve_with_term(self, g: int, idx: int, fallback: Any):
        """(payload, term) to apply for committed ``(g, idx)``: the
        candidate whose term matches the device's committed entry.
        Falls back to the representative binding (term None) when no
        candidates were tracked (non-split group, or a payload that
        arrived without churn)."""
        cands = self._cands.get((g, idx))
        if not cands:
            return fallback, None
        if len(cands) == 1:
            term, payload = next(iter(cands.items()))
            # Verify even the sole candidate against the committed
            # entry's ring term (ADVICE r03): a sender-side eviction
            # edge could leave only a stale-term candidate, and
            # applying it silently would diverge replicas — the ring
            # is the arbiter everywhere else, and the view is already
            # cached per tick.
            ct = self.committed_term(g, idx)
            if ct is not None and ct != term:
                return fallback, None
            return payload, term
        term = self.committed_term(g, idx)
        if term is not None and term in cands:
            return cands[term], term
        return fallback, None

    # -- outbound ---------------------------------------------------------

    def extract(self) -> Dict[int, dict]:
        """Pull the boundary lanes of the current outbox (stored as
        ``driver.inbox`` after a step) and build one wire-ready slab per
        peer process: ``{proc: {"msgs": [...], "payloads": [...],
        "snaps": [...]}}``.  Empty slabs are omitted."""
        if not self.split_gs:
            return {}
        mb = self.driver.inbox
        # One compiled slice (all fields in one executable) + one
        # device→host transfer — see the dispatch-cost note in
        # ``__init__``.
        sub = jax.device_get(self._slice_fn(mb))._asdict()
        slabs: Dict[int, dict] = {}
        snap_done = set()  # (proc, g): one blob per destination process
        for gi, g in enumerate(self.split_gs):
            owner = self.spec.owners[g]
            for src in self._owned[g]:
                for dst in self._remote[g]:
                    proc = owner[dst]
                    for prefix in _PREFIXES:
                        if not sub[prefix + "active"][gi, src, dst]:
                            continue
                        fields = {
                            f: _to_py(sub[f][gi, src, dst])
                            for f in mb._fields
                            if f.startswith(prefix)
                        }
                        slab = slabs.setdefault(
                            proc, {"msgs": [], "payloads": [], "snaps": []}
                        )
                        slab["msgs"].append((g, src, dst, prefix, fields))
                        if prefix == "ar_":
                            self._attach_ar_extras(
                                slab, proc, g, fields, snap_done
                            )
        self._maybe_gc()
        return slabs

    def _attach_ar_extras(self, slab, proc, g, fields, snap_done) -> None:
        """Payloads for the entries an append lane carries; the service
        state blob when the lane is an InstallSnapshot fast-forward."""
        if fields["ar_snap"]:
            # Keyed per (destination process, group): several peers can
            # need the same group's snapshot simultaneously and each
            # must get its own blob copy.
            if (proc, g) not in snap_done:
                snap_done.add((proc, g))
                upto, blob = self.service.snapshot_group(g)
                slab["snaps"].append((g, upto, blob))
            return
        prev, n = fields["ar_prev_idx"], fields["ar_n"]
        for e in range(n):
            idx = prev + 1 + e
            term = fields["ar_terms"][e]
            # Ship the candidate matching this lane's entry term — the
            # exact identity the receiver's device will consider.
            payload = self._cands.get((g, idx), {}).get(term)
            if payload is None:
                payload = self.driver.payloads.get((g, idx))
            if payload is None:
                continue  # binding evicted; device terms rule anyway
            slab["payloads"].append(
                (g, idx, term, self.service.export_payload(payload))
            )

    # -- inbound ----------------------------------------------------------

    def inject(self, slab: dict) -> None:
        """Merge a peer's slab: payloads/snapshots first (so entries
        never commit locally before their commands are materialized),
        then the mailbox lanes.  Lanes whose dst we do not own are
        ignored (misrouted or stale-spec messages)."""
        for g, upto, blob in slab.get("snaps", ()):
            if g in self.spec.owners:
                self._drop_below(g, upto)
                self.service.install_group_snapshot(g, upto, blob)
        for g, idx, term, wire in slab.get("payloads", ()):
            if g not in self.spec.owners:
                continue
            cands = self._cands.setdefault((g, idx), {})
            if term not in cands:
                cands[term] = self.service.import_payload(wire)
                if self.on_candidate is not None:
                    self.on_candidate(g, idx, term, cands[term])
            if (g, idx) not in self.driver.payloads:
                # Representative for the base machinery; resolve()
                # picks the term-correct candidate at apply time.
                self.driver.payloads[(g, idx)] = cands[term]

        # Lanes STAGE into host overlays; flush_staged merges them into
        # the device inbox in one compiled call before the next tick
        # (SplitFrontierMixin.pump).  Staging keeps the old
        # last-write-wins semantics per lane.
        for g, src, dst, prefix, fields in slab.get("msgs", ()):
            if g not in self.spec.owners or dst not in self._owned[g]:
                continue  # misrouted or stale-spec message
            gi = self._g_pos[g]
            self._stage_mask[prefix][gi, src, dst] = True
            for f, v in fields.items():
                self._stage_vals[f][gi, src, dst] = v
            self._stage_dirty = True

    def flush_staged(self) -> None:
        """Merge every staged lane into the device inbox — one compiled
        call per pump (called by the service's pump before the tick)."""
        if not self._stage_dirty:
            return
        # copy=True: the CPU backend may zero-copy these numpy staging
        # buffers, and dispatch is async — the ``m[:] = False`` reset
        # below (and the next pump's stage writes into _stage_vals)
        # would race the pending read, silently dropping staged
        # vote/append lanes (observed: split groups never electing when
        # the executable loads instantly from the persistent cache).
        self.driver.inbox = self._merge_fn(
            self.driver.inbox,
            {p: jnp.array(m, copy=True) for p, m in self._stage_mask.items()},
            {f: jnp.array(v, copy=True) for f, v in self._stage_vals.items()},
        )
        for m in self._stage_mask.values():
            m[:] = False
        self._stage_dirty = False

    # -- payload retention GC ---------------------------------------------

    def _maybe_gc(self) -> None:
        self._gc_countdown -= 1
        if self._gc_countdown > 0:
            return
        self._gc_countdown = self.GC_EVERY
        st = self.driver.np_state()
        for g in self.split_gs:
            floor = int(min(st["base"][g, p] for p in self._owned[g]))
            # Persistence holds candidates back to its snapshot
            # frontier (service-state replay needs their commands).
            floor = min(floor, self.gc_floor.get(g, floor))
            self._drop_below(g, floor, evict=False)

    def _drop_below(self, g: int, floor: int, evict: bool = True) -> None:
        """Drop retained payloads/candidates at or below ``floor``
        (covered by the ring floor / an installed snapshot).  ``evict``
        fails their tickets — used on snapshot install, where a locally
        bound command below the new frontier can never resolve here."""
        for (gg, idx) in list(self.driver.payloads.keys()):
            if gg == g and idx <= floor:
                payload = self.driver.payloads.pop((gg, idx))
                if evict and self.driver.on_payload_evicted:
                    self.driver.on_payload_evicted(payload)
        for (gg, idx) in list(self._cands.keys()):
            if gg == g and idx <= floor:
                for payload in self._cands.pop((gg, idx)).values():
                    if evict and self.driver.on_payload_evicted:
                        self.driver.on_payload_evicted(payload)


def _to_py(v):
    """numpy scalar/array -> plain python for the wire codec."""
    a = np.asarray(v)
    if a.ndim == 0:
        return a.item()
    return a.tolist()


class SplitFrontierMixin:
    """The split-mode service scaffolding shared by :class:`SplitKV`
    and :class:`~multiraft_tpu.engine.split_shard.SplitShardKV`: the
    host-paced compaction clamp and the lost-leadership flush.  The
    host class must set ``self.peering`` (by :class:`SplitPeering`),
    ``self._flush_countdown``, and implement ``_ticket_of(payload)``.
    """

    FLUSH_EVERY = 16

    def _ticket_of(self, payload):  # pragma: no cover - abstract
        raise NotImplementedError

    def pump(self, n_ticks: int = 1, **kw) -> None:
        """Merge staged peer lanes into the device inbox (one compiled
        call — see SplitPeering.flush_staged) before ticking.  A lane
        staged just before an edge cut in the same window merges anyway
        — equivalent to a message that arrived right before the cut,
        which the at-most-once model already admits."""
        if self.peering is not None:
            self.peering.flush_staged()
        super().pump(n_ticks, **kw)

    def _pre_sweep(self) -> None:
        """The host half of ``host_paced_compaction``: raise the
        device's ``applied`` to the PREVIOUS sweep's host frontier
        (clipped into [base, commit] per replica).  Compaction then
        never passes an index this sweep is about to apply, so term
        arbitration (SplitPeering.resolve) can always read the
        committed entry's term from the ring; the ring still drains at
        one-pump lag, keeping ingest capacity available.  One compiled
        call per pump (the uncompiled form cost ~3 dispatches on the
        per-tick hot path)."""
        if self.peering is None:
            return
        fn = getattr(self, "_paced_fn", None)
        if fn is None:
            fn = self._paced_fn = jax.jit(
                lambda applied, base, commit, upto: jnp.maximum(
                    applied, jnp.clip(upto[:, None], base, commit)
                )
            )
        st = self.driver.state
        self.driver.state = st._replace(
            applied=fn(
                st.applied, st.base, st.commit,
                jnp.asarray(np.asarray(self.applied_upto, np.int32)),
            )
        )

    def _flush_lost_leadership(self) -> None:
        """A process that lost leadership holds work no local accept
        will resolve: unbound backlog commands, and bound-but-
        uncommitted payloads whose tickets would otherwise wedge.
        Fail both so clients re-route — the batched analog of kvraft
        resolving every waiter ErrWrongLeader on a term change
        (reference: kvraft/server.go:98-128).  Failing is safe even
        when the entry later commits via the new leader: the client
        resubmits under the same (client_id, command_id) and dedup
        absorbs the duplicate."""
        self._flush_countdown -= 1
        if self._flush_countdown > 0:
            return
        self._flush_countdown = self.FLUSH_EVERY
        drv = self.driver
        have_backlog = any(drv.backlog[g] for g in range(drv.cfg.G))
        have_tickets = any(
            (t := self._ticket_of(p)) is not None and not t.done
            for p in drv.payloads.values()
        )
        if not have_backlog and not have_tickets:
            return
        leaders = drv.leaders_per_group()
        for g in range(drv.cfg.G):
            if drv.backlog[g] and leaders[g] == 0:
                for payload in drv._pending_payloads.pop(g, []):
                    self._on_evicted(payload)
                drv.backlog[g] = 0
        if have_tickets:
            for (g, _idx), payload in drv.payloads.items():
                ticket = self._ticket_of(payload)
                if (
                    leaders[g] == 0
                    and ticket is not None and not ticket.done
                ):
                    # Fail the ticket but KEEP the payload: if this
                    # process regains leadership the entry may still
                    # commit and must apply with its command.
                    self._on_evicted(payload)


class SplitKV(SplitFrontierMixin, BatchedKV):
    """KV state machine for split groups: every hosting process applies
    the same committed log to its own copy (the reference's per-server
    apply loop, kvraft/server.go:98-128, across processes), so client
    traffic can fail over to whichever process owns the new leader.

    Divergences from :class:`BatchedKV` (documented):

    * **Gets ride the log.**  The sole-acker ReadIndex collapse
      (kv.py:get) is single-process reasoning; across processes the
      simple, always-correct rule is the reference's own — reads are
      log entries too (SURVEY §3.4 "no lease/read-index optimization
      anywhere").
    * **Leadership is a submission gate.**  ``submit_local`` fails fast
      when no owned slot leads the group; the serving layer replies
      ErrWrongLeader and the clerk retries the peer process (reference
      clerk rotation, kvraft/client.go:47-71).
    * Payloads are retained for resend and disambiguated by entry term
      (see :class:`SplitPeering`), stripped of tickets on the wire —
      the remote process applies with ``ticket=None``; only the
      ingesting process acks.
    """

    def __init__(self, driver: EngineDriver,
                 record_groups: Optional[List[int]] = None) -> None:
        super().__init__(driver, record_groups=record_groups)
        self.retain_payloads = True
        self.peering: Optional[SplitPeering] = None  # set by SplitPeering
        self._flush_countdown = self.FLUSH_EVERY
        # Persistence hooks.  on_applied: (g, idx, term, payload) for
        # every applied entry of a split group (term -1 = fallback
        # apply; the payload itself then carries the op for the WAL) —
        # the service-state redo log.  on_snapshot_installed: a peer's
        # InstallSnapshot blob just replaced group state.
        self.on_applied = None
        self.on_snapshot_installed = None

    # -- wire adapters (used by SplitPeering) ------------------------------

    @staticmethod
    def export_payload(payload) -> tuple:
        op, _ticket = payload
        return (op.op, op.key, op.value, op.client_id, op.command_id)

    @staticmethod
    def import_payload(wire) -> tuple:
        o, key, value, cid, cmd = wire
        return (KVOp(op=o, key=key, value=value, client_id=cid,
                     command_id=cmd), None)

    def snapshot_group(self, g: int) -> Tuple[int, dict]:
        """Applied state of group ``g`` for an InstallSnapshot slab:
        the kvraft snapshot payload (KV map + dup table,
        reference: kvraft/server.go:159-183) at the applied frontier."""
        return self.applied_upto[g], {
            "data": dict(self.data[g]),
            "sessions": dict(self.sessions[g]),
        }

    # persist_group/restore_group/replay_apply: the service adapter
    # trio SplitPersistence drives (shared contract with SplitShardKV).
    persist_group = snapshot_group

    def restore_group(self, g: int, upto: int, blob: dict) -> None:
        self.data[g] = dict(blob["data"])
        self.sessions[g] = dict(blob["sessions"])
        self.applied_upto[g] = upto

    def replay_apply(self, g: int, idx: int, payload) -> None:
        """Redo one recovered applied entry onto host state — the same
        apply function as the live path (engine/kv.py), so recovery
        can never drift from serving semantics."""
        apply_kv_op(self.data[g], self.sessions[g], payload[0])

    def install_group_snapshot(self, g: int, upto: int, blob: dict) -> None:
        if upto <= self.applied_upto[g]:
            return  # stale slab: we are already past it
        self.restore_group(g, upto, blob)
        if self.on_snapshot_installed is not None:
            # Persistence must capture this state before the next
            # pump's raft slice (whose base jumped with it) is fsynced
            # — else a crash in the window restores base past a service
            # state that never saw the blob.
            self.on_snapshot_installed(g)

    # -- apply: term-arbitrated payload choice ------------------------------

    def _ticket_of(self, payload):
        return payload[1]

    def _apply(self, g: int, idx: int, payload: Any, now: int) -> None:
        if self.peering is not None and g in self.peering.spec.owners:
            payload, term = self.peering.resolve_with_term(g, idx, payload)
            super()._apply(g, idx, payload, now)
            if self.on_applied is not None:
                self.on_applied(
                    g, idx, -1 if term is None else term, payload
                )
            return
        super()._apply(g, idx, payload, now)

    # -- leadership-gated submission --------------------------------------

    def local_leader(self, g: int) -> Optional[int]:
        """Owned slot currently leading ``g``, if any (remote slots are
        alive=False locally, so leader_of only ever reports owned
        ones)."""
        return self.driver.leader_of(g)

    def submit_local(self, g: int, op: KVOp) -> Optional[Ticket]:
        """Submit iff an owned slot leads ``g``; None = wrong process
        (the serving layer's ErrWrongLeader)."""
        if self.local_leader(g) is None:
            return None
        return self.submit(g, op)

    # -- pump hooks --------------------------------------------------------

    def _post_pump(self) -> None:
        self._flush_lost_leadership()
