"""The SHARDED stack over cross-process replica groups — per-server
failure domains for shardkv, the TPU-native way.

:mod:`engine/shardkv` runs the whole sharded deployment (config RSM at
engine group 0 + every replica group) inside ONE process; losing the
process loses every peer of every group at once — durability, not
availability.  The reference's shardkv spec is precisely about
per-server crashes *within* replica groups while migration continues
(reference: shardkv/config.go:204-262 per-group server matrices;
shardkv/test_test.go:97-216 old-owner shutdown mid-migration).  This
module restores that failure model: each participating process runs the
SAME engine shapes and applies EVERY group's log, but owns only a
subset of each group's P peer slots (:class:`~multiraft_tpu.engine.
split.SplitSpec`); consensus crosses processes via the per-tick slab
exchange (:class:`~multiraft_tpu.engine.split.SplitPeering`), so a
process death loses only its owned slots and any group whose survivors
hold a quorum keeps serving with every acknowledged write intact from
replication alone — no WAL replay.

Cross-process migration WITHOUT new RPCs — state-driven orchestration:

Because every process applies every group's log (slab replication
materializes all of them), the sim backend's pull/GC RPC handshakes
collapse into observations of local applied state:

* **pull** — the puller's leader-owner reads the source group's shard
  from its OWN applied copy, gated on that copy having applied the
  same config number (the ErrNotReady gate);
* **Challenge-1 delete** — proposed into the source group's log by
  whichever process owns the SOURCE group's leader, once it OBSERVES
  (in its applied copy of the new owner's log) that the insert
  committed (slot state GCING/SERVING at the same config);
* **confirm (GCING→SERVING)** — proposed by the new owner's
  leader-owner once it OBSERVES the source slot leave BEPULLING.

Every step is driven from replicated state, not per-process callback
chains, so it is idempotent and leader-failover-proof by construction:
kill any minority owner mid-handshake and whichever process next owns
the relevant leader re-derives exactly the missing step.  (The fleet
backend's ``remote_fetch``/``remote_delete`` hooks solve the DIFFERENT
problem of groups hosted by disjoint processes; here all groups are
replicated everywhere and the hooks stay None.)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..services.shardctrler import NSHARDS, Config
from ..services.shardkv import BEPULLING, GCING, PULLING, SERVING
from .host import EngineDriver
from .shardkv import (
    BatchedShardKV,
    ShardTicket,
    _ClientOp,
    _ConfigOp,
    _ConfirmOp,
    _CtrlOp,
    _DeleteOp,
    _InsertOp,
    _ShardSlot,
)
from .split import SplitFrontierMixin

__all__ = ["SplitShardKV"]


import dataclasses


@dataclasses.dataclass
class _NoOp:
    """Leader barrier entry.  Raft's current-term guard (reference:
    raft/raft_append_entry.go:98) means a new leader cannot commit
    prior-term entries until one of ITS OWN commits — a failover can
    strand a committed-elsewhere suffix (a config change, an insert)
    at the survivors forever if nothing new is proposed.  Client
    groups unwedge via traffic; migration steps wait on *state* that
    waits on the commit, so the split orchestration proposes this
    no-op into any led group whose commit frontier stalls below its
    last index (the classic leader no-op, stall-triggered rather than
    per-election so steady state pays nothing)."""

    ticket: Optional[ShardTicket] = None


def _config_to_wire(c: Config) -> list:
    return [c.num, list(c.shards),
            [[gid, list(srv)] for gid, srv in sorted(c.groups.items())]]


def _config_from_wire(w) -> Config:
    num, shards, groups = w
    return Config(num=num, shards=list(shards),
                  groups={int(g): list(s) for g, s in groups})


class SplitShardKV(SplitFrontierMixin, BatchedShardKV):
    """:class:`BatchedShardKV` with its peer slots split over processes.

    Construct one per process (same ``EngineConfig`` with
    ``host_paced_compaction=True``, same gid layout) and attach a
    :class:`~multiraft_tpu.engine.split.SplitPeering` with the SAME
    ``owners`` map everywhere.  Engine group 0 (the config RSM) splits
    like any other group — admin ops land at whichever process owns its
    leader (``submit`` gates; the serving clerk rotates).

    Divergences from the single-process base (documented):

    * ``get_fast`` is disabled — the sole-acker ReadIndex collapse is
      single-process reasoning; reads ride the log (reference
      semantics, SURVEY §3.4).
    * Proposals are leadership-gated per engine group: only the process
      owning a group's current leader orchestrates for it (config
      advance, pulls, confirms) or accepts client ops; Challenge-1
      deletes are proposed by the SOURCE group's leader-owner (see the
      module docstring's state-driven handshake).
    * The ctrler session id is per-process (``1000 + me``) so two
      processes' admin proposals cannot collide in the dedup table.
    """

    # Pumps a led group's commit frontier may sit strictly below its
    # last index without progress before a no-op barrier is proposed
    # (see :class:`_NoOp`).  Normal replication clears the gap in 2-3
    # pumps; only a post-failover stall reaches the threshold.
    STALL_PUMPS = 24

    def __init__(self, driver: EngineDriver) -> None:
        super().__init__(driver)
        self.retain_payloads = True
        self.peering = None  # set by SplitPeering
        self._flush_countdown = self.FLUSH_EVERY
        # Stall tracking for the no-op barrier: g -> [commit, pumps].
        self._stall: Dict[int, list] = {}
        self._noop_tickets: Dict[int, ShardTicket] = {}
        # Persistence hooks (parity with SplitKV's; a durable sharded
        # split server wires these).
        self.on_applied = None
        self.on_snapshot_installed = None

    # SplitPeering calls this after construction; pick the per-process
    # ctrler identity up from the spec then.
    def _attach_peering(self, peering) -> None:
        self._ctrl_client_id = 1000 + peering.spec.me

    # -- wire adapters (used by SplitPeering) ------------------------------

    @staticmethod
    def export_payload(payload) -> list:
        op = payload
        if isinstance(op, _ClientOp):
            return ["c", op.op, op.key, op.value, op.client_id,
                    op.command_id]
        if isinstance(op, _CtrlOp):
            arg = op.arg
            if op.kind == "join":
                arg = [[gid, list(s)] for gid, s in sorted(arg.items())]
            elif op.kind == "move":
                arg = list(arg)
            else:
                arg = list(arg)
            return ["t", op.kind, arg, op.client_id, op.command_id]
        if isinstance(op, _ConfigOp):
            return ["f", _config_to_wire(op.config)]
        if isinstance(op, _InsertOp):
            return ["i", op.config_num, op.shard, dict(op.data),
                    {int(k): int(v) for k, v in op.latest.items()}]
        if isinstance(op, _DeleteOp):
            return ["d", op.config_num, op.shard]
        if isinstance(op, _ConfirmOp):
            return ["m", op.config_num, op.shard]
        if isinstance(op, _NoOp):
            return ["n"]
        raise TypeError(f"unknown shardkv payload {type(op).__name__}")

    @staticmethod
    def import_payload(wire):
        tag = wire[0]
        if tag == "c":
            _, op, key, value, cid, cmd = wire
            return _ClientOp(op=op, key=key, value=value, client_id=cid,
                             command_id=cmd, ticket=None)
        if tag == "t":
            _, kind, arg, cid, cmd = wire
            if kind == "join":
                arg = {int(g): list(s) for g, s in arg}
            elif kind == "move":
                arg = tuple(arg)
            else:
                arg = list(arg)
            return _CtrlOp(kind=kind, arg=arg, client_id=cid,
                           command_id=cmd, ticket=None)
        if tag == "f":
            return _ConfigOp(config=_config_from_wire(wire[1]), ticket=None)
        if tag == "i":
            _, num, shard, data, latest = wire
            return _InsertOp(config_num=num, shard=shard, data=dict(data),
                             latest={int(k): int(v)
                                     for k, v in latest.items()},
                             ticket=None)
        if tag == "d":
            return _DeleteOp(config_num=wire[1], shard=wire[2], ticket=None)
        if tag == "m":
            return _ConfirmOp(config_num=wire[1], shard=wire[2], ticket=None)
        if tag == "n":
            return _NoOp(ticket=None)
        raise TypeError(f"unknown shardkv wire tag {tag!r}")

    # -- group snapshots (InstallSnapshot slab blobs) ----------------------

    def snapshot_group(self, g: int) -> Tuple[int, dict]:
        """Applied state of ENGINE group ``g`` for an InstallSnapshot
        slab: the ctrler history for group 0, the replica's shard
        slots otherwise (pending tickets are per-process volatile state
        and never travel)."""
        if g == 0:
            return self.applied_upto[0], {
                "kind": "ctrl",
                "configs": [_config_to_wire(c) for c in self.configs],
                "latest": {int(k): int(v)
                           for k, v in self._ctrl_latest.items()},
            }
        rep = self.reps[self._l2g[g]]
        return self.applied_upto[g], {
            "kind": "rep",
            "cur": _config_to_wire(rep.cur),
            "prev": _config_to_wire(rep.prev),
            "shards": {
                int(s): [sl.state, dict(sl.data),
                         {int(k): int(v) for k, v in sl.latest.items()}]
                for s, sl in rep.shards.items()
            },
        }

    # persist_group/restore_group/replay_apply: the service adapter
    # trio SplitPersistence drives (shared contract with SplitKV) —
    # the durable sharded split reuses the same snapshot + redo-log
    # machinery the plain-KV split peers have.
    def persist_group(self, g: int) -> Tuple[int, dict]:
        return self.snapshot_group(g)

    def replay_apply(self, g: int, idx: int, payload) -> None:
        """Redo one recovered applied entry through the SAME dispatch
        the live path uses (dedup tables and config/state gates make
        anything already inside the snapshot a no-op), with the
        durability hooks suppressed so replay does not re-log its own
        records."""
        if isinstance(payload, _NoOp):
            return
        hooks = (self.on_applied, self.on_insert, self.on_delete,
                 self.on_confirm, self.on_write, self.on_ctrl)
        (self.on_applied, self.on_insert, self.on_delete,
         self.on_confirm, self.on_write, self.on_ctrl) = (None,) * 6
        try:
            BatchedShardKV._apply(self, g, idx, payload, 0)
        finally:
            (self.on_applied, self.on_insert, self.on_delete,
             self.on_confirm, self.on_write, self.on_ctrl) = hooks

    def install_group_snapshot(self, g: int, upto: int, blob: dict) -> None:
        if upto <= self.applied_upto[g]:
            return  # stale slab: we are already past it
        self.restore_group(g, upto, blob)
        if self.on_snapshot_installed is not None:
            self.on_snapshot_installed(g)

    def restore_group(self, g: int, upto: int, blob: dict) -> None:
        if blob["kind"] == "ctrl":
            import jax.numpy as jnp
            import numpy as np

            self.configs = [_config_from_wire(w) for w in blob["configs"]]
            self._ctrl_latest = {int(k): int(v)
                                 for k, v in blob["latest"].items()}
            self._route = jnp.asarray(
                np.array(self.configs[-1].shards, np.int32)
            )
        else:
            rep = self.reps[self._l2g[g]]
            rep.cur = _config_from_wire(blob["cur"])
            rep.prev = _config_from_wire(blob["prev"])
            rep.shards = {
                int(s): _ShardSlot(
                    state=st, data=dict(data),
                    latest={int(k): int(v) for k, v in lat.items()},
                )
                for s, (st, data, lat) in blob["shards"].items()
            }
            rep.pending_config = None
            rep.pending_insert.clear()
            rep.pending_delete.clear()
            rep.pending_confirm.clear()
        self.applied_upto[g] = upto

    # -- apply: term-arbitrated payload choice -----------------------------

    def _ticket_of(self, payload):
        return getattr(payload, "ticket", None)

    def _apply(self, g: int, idx: int, payload: Any, now: int) -> None:
        if self.peering is not None and g in self.peering.spec.owners:
            payload, term = self.peering.resolve_with_term(g, idx, payload)
            if isinstance(payload, _NoOp):
                self._resolve(payload, now)
            else:
                super()._apply(g, idx, payload, now)
            if self.on_applied is not None:
                self.on_applied(
                    g, idx, -1 if term is None else term, payload
                )
            return
        if isinstance(payload, _NoOp):
            self._resolve(payload, now)
            return
        super()._apply(g, idx, payload, now)

    # -- leadership-gated client surface -----------------------------------

    def local_leader(self, gid: int) -> Optional[int]:
        """Owned slot currently leading ``gid``'s engine group, if any
        (remote slots are alive=False locally)."""
        return self.driver.leader_of(self._g2l[gid])

    def submit_local(self, gid: int, op: str, key: str, value: str = "",
                     client_id: int = 0,
                     command_id: int = 0) -> Optional[ShardTicket]:
        """Submit iff an owned slot leads ``gid``; None = wrong process
        (the serving layer's ErrWrongLeader)."""
        if self.local_leader(gid) is None:
            return None
        return self.submit(gid, op, key, value, client_id, command_id)

    def ctrl_local(self, kind: str, arg: Any,
                   command_id: Optional[int] = None,
                   client_id: Optional[int] = None
                   ) -> Optional[ShardTicket]:
        """Admin op iff an owned slot leads the config RSM (engine
        group 0); None = wrong process.  Callers that may retry the
        same op AT ANOTHER PROCESS must pass their own ``client_id``
        (+ a stable ``command_id``): the per-process default session
        would let two issuers' independent command numbering collide in
        the dedup table and silently swallow an op as a "duplicate"."""
        if self.driver.leader_of(0) is None:
            return None
        return self._ctrl(kind, arg, command_id, client_id=client_id)

    def get_fast(self, key: str) -> ShardTicket:
        raise NotImplementedError(
            "get_fast is single-process reasoning (sole-acker ReadIndex); "
            "split deployments ride reads through the log"
        )

    # -- pump hooks --------------------------------------------------------

    def _post_pump(self) -> None:
        if self._orchestrate_enabled:
            self._orchestrate()
        self._flush_lost_leadership()

    # -- split-aware orchestration ----------------------------------------

    def _orchestrate(self) -> None:
        """Leadership-gated, state-driven form of the base sweep (see
        module docstring).  Each process proposes only into logs whose
        leader it currently owns; the Challenge-1 handshake is derived
        from replicated state on both sides, so any step a dead process
        never took is re-derived by the next leader owner."""
        if self.peering is None:
            return super()._orchestrate()
        # ONE device-state snapshot per sweep: per-gid local_leader()
        # calls would each materialize the full state (np_state) — at a
        # 2 ms pump cadence that is the dominant host cost.
        st = self.driver.np_state()
        lead = (st["role"] == 2) & st["alive"]
        led_term = np.where(lead, st["term"], -1)
        led_slot = np.where(lead.any(axis=1), led_term.argmax(axis=1), -1)
        self._noop_barriers(st, led_slot)
        latest = self.configs[-1]
        for gid in self.gids:
            rep = self.reps[gid]
            if led_slot[self._g2l[gid]] < 0:
                continue  # this group's proposals belong elsewhere
            # (a) config advance — in order, never mid-migration
            # (mirror of shardkv._orchestrate step (a)).
            if (
                latest.num > rep.cur.num
                and not self._live(rep.pending_config)
                and all(sh.state == SERVING for sh in rep.shards.values())
            ):
                nxt = self.configs[rep.cur.num + 1].clone()
                t = ShardTicket(group=gid)
                rep.pending_config = t
                self.driver.start(
                    self._g2l[gid], _ConfigOp(config=nxt, ticket=t)
                )
            for s in range(NSHARDS):
                sh = rep.shards[s]
                # (b) pull: from the LOCAL applied copy of the source
                # group (every process materializes all groups), gated
                # on that copy having applied the same config — the
                # ErrNotReady handshake as an applied-frontier check.
                if sh.state == PULLING and not self._live(
                    rep.pending_insert.get(s)
                ):
                    if self.migration_paused:
                        continue
                    src = self.reps.get(rep.prev.shards[s])
                    if src is None or src.cur.num < rep.cur.num:
                        continue  # our copy of the source lags; retry
                    t = ShardTicket(group=gid)
                    rep.pending_insert[s] = t
                    self.driver.start(
                        self._g2l[gid],
                        _InsertOp(
                            config_num=rep.cur.num,
                            shard=s,
                            data=dict(src.shards[s].data),
                            latest=dict(src.shards[s].latest),
                            ticket=t,
                        ),
                    )
                # (c2) confirm: the delete's effect is OBSERVED in our
                # applied copy of the source group — its slot left
                # BEPULLING at our config (deleted, or re-owned by a
                # later config).  Prev owner 0 never happens (PULLING
                # requires a nonzero previous owner).
                elif sh.state == GCING and not self._live(
                    rep.pending_confirm.get(s)
                ):
                    if self.migration_paused:
                        continue
                    src = self.reps.get(rep.prev.shards[s])
                    deleted = (
                        src is not None
                        and src.cur.num >= rep.cur.num
                        and (src.cur.num > rep.cur.num
                             or src.shards[s].state != BEPULLING)
                    )
                    if not deleted:
                        continue  # source leader-owner will delete
                    t = ShardTicket(group=gid)
                    rep.pending_confirm[s] = t
                    self.driver.start(
                        self._g2l[gid],
                        _ConfirmOp(config_num=rep.cur.num, shard=s,
                                   ticket=t),
                    )
        # (c1) Challenge-1 deletes: proposed into logs WE lead, on
        # behalf of pullers observed (in replicated state) to have the
        # data.  Delete-after-insert safety: GCING/SERVING at the same
        # config proves the insert committed — until then the source's
        # BEPULLING copy may be the only one.
        for src_gid in self.gids:
            if led_slot[self._g2l[src_gid]] < 0 or self.migration_paused:
                continue
            src = self.reps[src_gid]
            for s in range(NSHARDS):
                if src.shards[s].state != BEPULLING:
                    continue
                new_gid = src.cur.shards[s]
                new_rep = self.reps.get(new_gid)
                if new_rep is None:
                    continue
                has_data = (
                    new_rep.cur.num >= src.cur.num
                    and (new_rep.cur.num > src.cur.num
                         or new_rep.shards[s].state in (GCING, SERVING))
                )
                if has_data and not self._live(src.pending_delete.get(s)):
                    t = ShardTicket(group=src_gid)
                    src.pending_delete[s] = t
                    self.driver.start(
                        self._g2l[src_gid],
                        _DeleteOp(config_num=src.cur.num, shard=s,
                                  ticket=t),
                    )

    def _noop_barriers(self, st, led_slot) -> None:
        """Detect led groups whose commit frontier has stalled strictly
        below their last log index and propose a :class:`_NoOp` barrier
        (the leader no-op that lets the current-term guard commit the
        inherited suffix after a failover).  ``st``/``led_slot`` come
        from the caller's single per-sweep snapshot."""
        drv = self.driver
        for g in range(drv.cfg.G):
            p = int(led_slot[g])
            if p < 0:
                self._stall.pop(g, None)
                continue
            commit = int(st["commit"][g, p])
            last = int(st["base"][g, p] + st["log_len"][g, p])
            if commit >= last:
                self._stall.pop(g, None)
                continue
            rec = self._stall.setdefault(g, [commit, 0])
            if rec[0] != commit:
                rec[0], rec[1] = commit, 0
                continue
            rec[1] += 1
            if rec[1] < self.STALL_PUMPS or self._live(
                self._noop_tickets.get(g)
            ):
                continue
            t = ShardTicket(group=g)
            self._noop_tickets[g] = t
            rec[1] = 0
            drv.start(g, _NoOp(ticket=t))
