"""Multi-chip sharding for the batched engine — the production mesh
recipe, shared by :class:`EngineDriver`, ``bench.py``, and
``__graft_entry__.dryrun_multichip``.

The groups axis is embarrassingly parallel (consensus traffic never
crosses a group boundary — SURVEY §2.2), so the whole engine shards
over a 1-D ``Mesh`` named ``"groups"`` with **zero collectives** in the
compiled step.  Two properties make that work:

* every per-group tensor (leading dim ``G``) gets
  ``PartitionSpec("groups")``; scalars/keys are replicated;
* the step runs under ``jax.shard_map``, so the steady-state fast-path
  ``lax.cond`` predicates (global reductions in ``tick_impl``) evaluate
  *per device* — under plain GSPMD jit they would lower to scalar
  all-reduces (measured: 2 all-reduces/tick).

Scalar metrics are returned as per-device lanes (shape ``[n_devices]``,
sharded) instead of ``psum``-ed, keeping the zero-collective guarantee;
hosts sum them lazily.

Cross-host placement note: a (groups-sharded) mesh spanning hosts puts
disjoint group ranges on each host's chips; chip↔chip traffic is zero
for consensus, and client routing to the owning host is the transport
layer's job (``distributed/``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax only exports it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .core import (
    METRIC_KEYS,
    TRACE_KEYS,
    EngineConfig,
    EngineState,
    Mailbox,
    tick_impl,
)

__all__ = [
    "group_pspec",
    "shard_arrays",
    "make_sharded_tick",
    "make_sharded_run_ticks",
    "assert_zero_collectives",
]

# Collective ops that must never appear in the compiled consensus step.
_COLLECTIVES = ("all-reduce", "all-gather", "collective-permute",
                "reduce-scatter", "all-to-all")


def group_pspec(cfg: EngineConfig, x) -> P:
    """PartitionSpec for one engine array: shard the leading axis iff
    it is the groups axis; everything else is replicated."""
    sharded = getattr(x, "ndim", 0) >= 1 and x.shape and x.shape[0] == cfg.G
    return P("groups") if sharded else P()


def shard_arrays(cfg: EngineConfig, mesh: Mesh, tree):
    """``device_put`` a state/mailbox pytree with the groups axis split
    over the mesh."""
    put = lambda x: jax.device_put(
        x, NamedSharding(mesh, group_pspec(cfg, x))
    )
    return jax.tree.map(put, tree)


def _local_cfg(cfg: EngineConfig, mesh: Mesh) -> EngineConfig:
    n = mesh.devices.size
    if cfg.G % n != 0:
        raise ValueError(
            f"G={cfg.G} must divide evenly over {n} mesh devices"
        )
    return dataclasses.replace(cfg, G=cfg.G // n)


def make_sharded_tick(
    cfg: EngineConfig, mesh: Mesh
) -> Callable[[EngineState, Mailbox, jnp.ndarray, jax.Array], Tuple]:
    """The full engine tick under ``shard_map``: each device advances
    its local slice of groups.  Returns a jitted
    ``step(state, inbox, new_cmds, key) -> (state, outbox, metrics)``
    where scalar metrics come back as per-device lanes (sum on host).
    Per-group metric vectors keep their global [G] shape."""
    lcfg = _local_cfg(cfg, mesh)

    def local_step(state, inbox, new_cmds, key):
        st, mb, m = tick_impl(lcfg, state, inbox, new_cmds, key)
        # Scalars become one lane per device (out_spec "groups" then
        # concatenates them) — no psum, zero collectives.
        m = {
            k: (v[None] if v.ndim == 0 else v) for k, v in m.items()
        }
        return st, mb, m

    # Build in/out specs structurally: state/mailbox fields shard on
    # their leading (groups) axis; metrics lanes shard likewise.
    state_fields = EngineState._fields
    mailbox_fields = Mailbox._fields
    state_specs = EngineState(
        **{
            f: (P() if f == "tick_no" else P("groups"))
            for f in state_fields
        }
    )
    inbox_specs = Mailbox(**{f: P("groups") for f in mailbox_fields})
    metric_specs = {k: P("groups") for k in METRIC_KEYS}
    return jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(state_specs, inbox_specs, P("groups"), P()),
            out_specs=(state_specs, inbox_specs, metric_specs),
        )
    )


def make_sharded_run_ticks(
    cfg: EngineConfig, mesh: Mesh, n_ticks: int, ingest_per_tick: int
):
    """Device-resident multi-tick loop (the bench path) under the same
    shard_map recipe: ``lax.scan`` of the local tick per device, zero
    host round-trips and zero collectives.  Returns a jitted
    ``run(state, inbox, key) -> (state, inbox)``."""
    lcfg = _local_cfg(cfg, mesh)

    def local_run(state, inbox, key):
        new_cmds = jnp.full((lcfg.G,), ingest_per_tick, jnp.int32)

        def body(carry, i):
            st, mb = carry
            st, mb, _ = tick_impl(lcfg, st, mb, new_cmds, jax.random.fold_in(key, i))
            return (st, mb), None

        (state, inbox), _ = jax.lax.scan(
            body, (state, inbox), jnp.arange(n_ticks, dtype=jnp.int32)
        )
        return state, inbox

    state_specs = EngineState(
        **{
            f: (P() if f == "tick_no" else P("groups"))
            for f in EngineState._fields
        }
    )
    inbox_specs = Mailbox(**{f: P("groups") for f in Mailbox._fields})
    return jax.jit(
        shard_map(
            local_run,
            mesh=mesh,
            in_specs=(state_specs, inbox_specs, P()),
            out_specs=(state_specs, inbox_specs),
        )
    )


def make_sharded_run_ticks_traced(
    cfg: EngineConfig, mesh: Mesh, n_ticks: int, ingest_per_tick: int
):
    """``make_sharded_run_ticks`` + the per-tick trace records of
    ``core.run_ticks_traced`` (frontiers/accept terms, [n_ticks, G]
    sharded on the groups axis) — the bench's verified mode on a mesh,
    same zero-collective recipe."""
    lcfg = _local_cfg(cfg, mesh)

    def local_run(state, inbox, key):
        from .core import make_traced_body

        new_cmds = jnp.full((lcfg.G,), ingest_per_tick, jnp.int32)
        body = make_traced_body(lcfg, new_cmds, key)
        (state, inbox), rec = jax.lax.scan(
            body, (state, inbox), jnp.arange(n_ticks, dtype=jnp.int32)
        )
        return state, inbox, rec

    state_specs = EngineState(
        **{
            f: (P() if f == "tick_no" else P("groups"))
            for f in EngineState._fields
        }
    )
    inbox_specs = Mailbox(**{f: P("groups") for f in Mailbox._fields})
    rec_specs = {k: P(None, "groups") for k in TRACE_KEYS}
    return jax.jit(
        shard_map(
            local_run,
            mesh=mesh,
            in_specs=(state_specs, inbox_specs, P()),
            out_specs=(state_specs, inbox_specs, rec_specs),
        )
    )


def assert_zero_collectives(jitted, *example_args) -> str:
    """Compile ``jitted`` for the example args and assert the optimized
    HLO contains no cross-device collectives (the linear-scaling
    guarantee).  Returns the HLO text for further inspection."""
    hlo = jitted.lower(*example_args).compile().as_text()
    for coll in _COLLECTIVES:
        assert coll not in hlo, (
            f"unexpected {coll} in sharded engine step — the groups "
            f"axis must stay embarrassingly parallel"
        )
    return hlo
