"""Host-side driver for the batched engine.

Owns the tick loop: feeds the outbox back as the next inbox through the
tensorized fault model (drop masks + liveness — the labrpc semantics of
SURVEY §2.2 in dense form), maintains the Start() backlog and the
host-side command payload store keyed ``(group, index)`` (the device
only consensus-orders terms/indices), and accumulates metrics.

This is also where crash/restart surgery happens: a "crashed" replica is
marked dead (mask) and, on restart, its volatile state is reset while
its persistent columns (term, vote, log, base) survive — the tensor
analog of the reference's Persister carryover
(reference: raft/config.go:113-142).
"""

from __future__ import annotations

import functools
import os
import pickle
import time
from collections import defaultdict
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.knobs import knob_bool
from ..utils.metrics import Metrics
from .core import (
    SCALAR_METRIC_KEYS,
    CANDIDATE,
    FOLLOWER,
    LEADER,
    EngineConfig,
    EngineState,
    Mailbox,
    empty_mailbox,
    init_state,
    tick,
)

__all__ = [
    "EngineDriver",
    "PayloadRun",
    "PayloadSlice",
    "apply_faults",
    "mask_active",
]


class PayloadRun:
    """A pending firehose run: ``rows`` (original frame row indices,
    submission order) of ``frame`` awaiting log slots in one group.
    Consumed incrementally by the binding loop — each accept batch
    takes a prefix as one :class:`PayloadSlice`."""

    __slots__ = ("frame", "rows", "consumed")

    def __init__(self, frame: Any, rows: "np.ndarray") -> None:
        self.frame = frame
        self.rows = rows
        self.consumed = 0

    @property
    def remaining(self) -> int:
        return len(self.rows) - self.consumed

    def take(self, k: int) -> "PayloadSlice":
        s = PayloadSlice(self.frame, self.rows[self.consumed: self.consumed + k])
        self.consumed += k
        return s


class PayloadSlice:
    """A bound contiguous range of log slots carrying firehose rows:
    stored in ``driver.payloads`` keyed by its FIRST (group, index);
    covers ``len(rows)`` consecutive indices.  The frontier sweep
    applies it whole (or splits it at the commit frontier); eviction
    fails all its rows at once."""

    __slots__ = ("frame", "rows")

    def __init__(self, frame: Any, rows: "np.ndarray") -> None:
        self.frame = frame
        self.rows = rows

    @property
    def count(self) -> int:
        return len(self.rows)

    def split_head(self, k: int) -> "PayloadSlice":
        """Split off the first ``k`` rows; self keeps the tail."""
        head = PayloadSlice(self.frame, self.rows[:k])
        self.rows = self.rows[k:]
        return head

# The message channels' liveness fields; every fault transform (drop,
# partition, crash edge-kill) is a mask over exactly these.  Derived
# from the Mailbox schema so a new channel can't bypass fault injection.
_ACTIVE_FIELDS = tuple(f for f in Mailbox._fields if f.endswith("_active"))

# Channel prefix -> all fields of that channel (e.g. "ar_" -> ar_active,
# ar_term, ..., ar_snap).  The reorder fault mode lifts whole messages —
# every field of a channel slot — out of the stream and redelivers them
# ticks later, so it needs the grouping, not just the active bits.
_CHANNELS = {
    f[: -len("active")]: tuple(
        g for g in Mailbox._fields if g.startswith(f[: -len("active")])
    )
    for f in _ACTIVE_FIELDS
}


def mask_active(mb: Mailbox, fn) -> Mailbox:
    """Apply ``fn(field_name, bool_array) -> bool_array`` over every
    ``*_active`` channel of the mailbox."""
    return mb._replace(**{k: fn(k, getattr(mb, k)) for k in _ACTIVE_FIELDS})


@functools.partial(jax.jit, static_argnums=(3,))
def apply_faults(
    mailbox: Mailbox, key: jax.Array, drop_prob: jnp.ndarray, cfg: EngineConfig
) -> Mailbox:
    """Drop each in-flight message independently with ``drop_prob`` —
    the dense-tensor form of labrpc's unreliable mode
    (reference: labrpc/labrpc.go:228-239,279-284; request and reply
    drops both land here because each direction is its own edge-slot)."""
    shape = (cfg.G, cfg.P, cfg.P)
    keys = jax.random.split(key, len(_ACTIVE_FIELDS))

    def drop(name, a):
        k = keys[_ACTIVE_FIELDS.index(name)]
        return a & (jax.random.uniform(k, shape) >= drop_prob)

    return mask_active(mailbox, drop)


class EngineDriver:
    def __init__(
        self, cfg: EngineConfig, seed: int = 0, mesh=None,
        check_zero_collectives: bool = True,
    ) -> None:
        """``mesh``: an optional 1-D ``jax.sharding.Mesh`` (axis
        ``"groups"``) — the driver then runs the production multi-chip
        recipe (engine/mesh.py): state/mailbox sharded on the groups
        axis, the tick under shard_map, and (by default) a compile-time
        assert that the step contains zero collectives."""
        self._init_host(cfg, seed)
        self.state: EngineState = init_state(cfg, jax.random.fold_in(self.key, 0))
        self.inbox: Mailbox = empty_mailbox(cfg)
        if mesh is not None:
            from .mesh import (
                assert_zero_collectives,
                make_sharded_tick,
                shard_arrays,
            )

            self.mesh = mesh
            self.state = shard_arrays(cfg, mesh, self.state)
            self.inbox = shard_arrays(cfg, mesh, self.inbox)
            self._mesh_tick = make_sharded_tick(cfg, mesh)
            if check_zero_collectives:
                import jax.numpy as _jnp

                assert_zero_collectives(
                    self._mesh_tick,
                    self.state,
                    self.inbox,
                    _jnp.zeros(cfg.G, _jnp.int32),
                    self.key,
                )

    def _init_host(self, cfg: EngineConfig, seed: int) -> None:
        """Host-side bookkeeping shared by __init__ and restore() —
        restore overwrites state/inbox from the checkpoint, so it must
        not pay for (or double-allocate) fresh device tensors."""
        self.cfg = cfg
        self.key = jax.random.PRNGKey(seed)
        self.drop_prob = 0.0
        # Per-edge enables [G, src, dst] — the dense form of labrpc's
        # per-ClientEnd enable/disable (reference: labrpc/labrpc.go:
        # 316-364; SURVEY §5.8 "partition by per-edge boolean enables").
        # Unlike ``alive`` (a crash mask that freezes the replica), a
        # partitioned replica stays live: timers run, candidacies fire,
        # but no message crosses a disabled edge.  ``replica_conn`` is
        # the per-replica connectivity that partition_replica derives
        # edges from (labrpc connect() semantics: an edge is up iff
        # *both* endpoints are connected).
        self.edge_up = np.ones((cfg.G, cfg.P, cfg.P), bool)
        self.replica_conn = np.ones((cfg.G, cfg.P), bool)
        self._edge_dev: Optional[jnp.ndarray] = None  # lazy device copy
        # Long-reordering mode (reference: labrpc/labrpc.go:289-299 —
        # 2/3 of replies delayed 200–2400 ms): each in-flight message is
        # independently pulled from the stream with ``reorder_prob`` and
        # redelivered reorder_min..reorder_max ticks later, landing
        # *behind* messages sent after it.  Held messages die if their
        # edge partitions or either endpoint restarts while in flight.
        self.reorder_prob = 0.0
        self.reorder_min, self.reorder_max = 2, 8
        self._np_rng = np.random.default_rng(seed ^ 0x5EED)
        self._delayed: list = []  # (release, prefix, (g,src,dst), fields)
        self.total_commits = 0
        self.backlog = np.zeros(cfg.G, np.int64)  # pending Start()s
        # Host-side payloads: (group, index) -> command.  The device
        # orders (term, index); data stays here (SURVEY §7.1).
        self.payloads: Dict[tuple, Any] = {}
        self._pending_payloads: Dict[int, list] = defaultdict(list)
        # Per-group bind high-water mark: an accept starting at or
        # below it is a truncation REBIND and triggers the stale-
        # binding eviction scan (see _bind_accepted).
        self._max_bound: Dict[int, int] = {}
        self.last_metrics: Dict[str, Any] = {}
        self.mesh = None
        self._mesh_tick = None
        # Structured counters (utils/metrics.py): ticks always; per-tick
        # wall latency samples when the tracer (diagnostic mode) is on.
        self.metrics = Metrics()
        self.tick = 0  # host mirror of the device tick counter
        # Called with the old payload when a (group, index) binding is
        # overwritten — i.e. the old command lost its slot to a leader
        # change and will never commit at that index.
        self.on_payload_evicted: Optional[Any] = None
        # Called as (g, idx, term) when a payload binds at ingest —
        # split-group peering records the accept term so a stale slab
        # from a deposed leader can never replace a newer local binding
        # (engine/split.py).  None = skip the extra metric readback.
        self.on_payload_bound: Optional[Any] = None
        # Optional utils.trace.Tracer: each tick becomes a wall-clock
        # span carrying its metrics.  The fused path buffers the spans
        # from the stacked metrics and emits once per pump; only the
        # serial loop pays a per-tick sync for them.
        self.tracer = None
        # Asynchronous engine pipeline (engine/pipeline.py).
        # MRT_ENGINE_PIPELINE=0 is the kill switch: serial per-tick
        # stepping plus the synchronous pump loop, for clean A/B.
        self._pipeline_on = knob_bool("MRT_ENGINE_PIPELINE")
        # Dispatched-but-not-completed PendingTicks, oldest first.
        # Bounded by the serving pipeline depth (MRT_PIPELINE_DEPTH).
        self._inflight: list = []

    # -- fault injection --------------------------------------------------

    def set_alive(self, g: int, p: int, alive: bool) -> None:
        """Partition/crash a replica (mask form of per-edge disable,
        reference: labrpc enable/disable)."""
        self.state = self.state._replace(
            alive=self.state.alive.at[g, p].set(alive)
        )

    def set_edge(self, g: int, src: int, dst: int, up: bool) -> None:
        """Enable/disable the directed message edge src→dst in group g
        (asymmetric partitions, labrpc's raw per-ClientEnd enable).
        Note: a later ``partition_replica`` call on either endpoint
        recomputes group g's edges from per-replica connectivity,
        overriding raw edge settings."""
        self.edge_up[g, src, dst] = up
        self._edges_changed()

    def partition_replica(self, g: int, p: int, connected: bool) -> None:
        """Cut (or heal) live replica (g, p): labrpc connect()
        semantics — an edge is up iff both endpoints are connected, so
        healing one replica never resurrects edges of another that is
        still partitioned (reference: labrpc/labrpc.go:316-364)."""
        self.replica_conn[g, p] = connected
        conn = self.replica_conn[g]
        self.edge_up[g] = conn[:, None] & conn[None, :]
        self._edges_changed()

    def _edges_changed(self) -> None:
        """In-flight messages on now-disabled edges die immediately —
        the partition takes effect this tick, not next.  That includes
        messages held in the reorder delay queue: a cut-then-heal
        between two ticks must not resurrect them."""
        self._edge_dev = None
        if not self.edge_up.all():
            self.inbox = self._mask_partitions(self.inbox)
        if self._delayed:
            self._delayed = [
                it for it in self._delayed if self.edge_up[it[2]]
            ]

    def _mask_partitions(self, mb: Mailbox) -> Mailbox:
        if self._edge_dev is None:
            # copy=True: zero-copy would alias the mutable edge_up
            # numpy mask into an async dispatch (see restore below).
            self._edge_dev = jnp.array(self.edge_up, copy=True)
        m = self._edge_dev
        return mask_active(mb, lambda _, a: a & m)

    def set_reorder(
        self, prob: float, min_ticks: int = 2, max_ticks: int = 8
    ) -> None:
        """Enable labrpc-style long reordering on the tensor transport:
        each message is delayed ``min_ticks..max_ticks`` ticks with
        probability ``prob`` (labrpc uses 2/3), arriving after traffic
        sent later — the non-FIFO delivery the conflict-backoff and
        staleness guards must survive (reference:
        raft/raft_append_entry.go:146-155)."""
        if not 0.0 <= prob <= 1.0 or min_ticks < 1 or max_ticks < min_ticks:
            raise ValueError("set_reorder: bad parameters")
        self.reorder_prob = float(prob)
        self.reorder_min, self.reorder_max = int(min_ticks), int(max_ticks)

    def _apply_reorder(self, mb: Mailbox) -> Mailbox:
        """Host-side delay queue over the dense mailbox.  A held message
        is redelivered once its release tick passes *and* its slot is
        free that tick (otherwise it waits — delaying further only
        increases reordering).  Test-path only: syncs the mailbox to
        host, so keep it off for throughput runs."""
        if self.reorder_prob == 0.0 and not any(
            release <= self.tick for release, *_ in self._delayed
        ):
            return mb  # nothing to pick, nothing due: skip the sync
        host = {f: np.array(getattr(mb, f)) for f in Mailbox._fields}
        rng = self._np_rng
        if self.reorder_prob > 0.0:
            for prefix, fields in _CHANNELS.items():
                act = host[prefix + "active"]
                pick = act & (rng.random(act.shape) < self.reorder_prob)
                for g, s, dst in np.argwhere(pick):
                    release = self.tick + int(
                        rng.integers(self.reorder_min, self.reorder_max + 1)
                    )
                    payload = {f: host[f][g, s, dst].copy() for f in fields}
                    # Chaos reorder buffer: every entry carries a
                    # release tick ≤ tick+reorder_max, so occupancy is
                    # bounded by reorder_max windows of traffic.
                    self._delayed.append(  # graftlint: disable=unbounded-queue
                        (release, prefix, (int(g), int(s), int(dst)), payload)
                    )
                act[pick] = False
        if self._delayed:
            held = []
            for item in self._delayed:
                release, prefix, (g, s, dst), payload = item
                if not self.edge_up[g, s, dst]:
                    continue  # partitioned while in flight: message dies
                if release <= self.tick and not host[prefix + "active"][g, s, dst]:
                    for f, v in payload.items():
                        host[f][g, s, dst] = v
                else:
                    held.append(item)
            self._delayed = held
        # copy=True: this mailbox becomes self.inbox, which downstream
        # callees DONATE (split flush_staged, run_ticks) — zero-copy
        # aliasing the host scratch arrays would hand XLA memory it
        # does not own (see restore below).
        return Mailbox(**{f: jnp.array(v, copy=True) for f, v in host.items()})

    def restart_replica(self, g: int, p: int) -> None:
        """Crash-restart: persistent columns (term/vote/log/base/commit
        floor) survive; volatile leadership state resets
        (reference: raft/raft.go:69 readPersist on Make)."""
        st = self.state
        self.state = st._replace(
            role=st.role.at[g, p].set(FOLLOWER),
            votes=st.votes.at[g, p].set(False),
            pre_votes=st.pre_votes.at[g, p].set(False),
            # Conservative lease on rebirth: wait out ELECT_MIN before
            # granting prevotes (volatile, like the vote tallies).
            last_heard=st.last_heard.at[g, p].set(st.tick_no),
            # Check-quorum clock is leadership-scoped (reseeded at
            # become_leader), so rebirth just zeroes it.
            last_ack=st.last_ack.at[g, p].set(0),
            # Applied rewinds to the snapshot floor: the service replays
            # the log above base (commit knowledge is volatile in Raft).
            commit=st.commit.at[g, p].set(st.base[g, p]),
            applied=st.applied.at[g, p].set(st.base[g, p]),
            alive=st.alive.at[g, p].set(True),
        )
        # In-flight messages to/from the old incarnation die — including
        # any held in the reorder delay queue.
        self.inbox = self._mask_edges(self.inbox, g, p)
        self._delayed = [
            it
            for it in self._delayed
            if not (it[2][0] == g and p in (it[2][1], it[2][2]))
        ]

    def _mask_edges(self, mb: Mailbox, g: int, p: int) -> Mailbox:
        return mask_active(
            mb, lambda _, a: a.at[g, p, :].set(False).at[g, :, p].set(False)
        )

    def reset_replica(self, g: int, p: int) -> None:
        """Wipe slot (g, p) to a FRESH INCARNATION — the re-add path
        (a removed peer index being reused for a new server), NOT the
        crash-restart path (:meth:`restart_replica`, where persistent
        state must survive).

        Beyond the restarted-row reset, this clears the OTHER replicas'
        per-column state about p: a stale ``votes[g, :, p]`` grant from
        the old incarnation would otherwise count toward a quorum of
        the new config at the old term, and a stale ``match_idx`` would
        let a leader commit over entries the new incarnation never
        acked.  ``alive`` is left False — :meth:`add_learner` raises it
        once the config view is seeded."""
        st = self.state
        self.state = st._replace(
            # Own row: blank server.
            term=st.term.at[g, p].set(0),
            voted_for=st.voted_for.at[g, p].set(-1),
            role=st.role.at[g, p].set(FOLLOWER),
            commit=st.commit.at[g, p].set(0),
            applied=st.applied.at[g, p].set(0),
            base=st.base.at[g, p].set(0),
            base_term=st.base_term.at[g, p].set(0),
            log_len=st.log_len.at[g, p].set(0),
            log_term=st.log_term.at[g, p].set(0),
            next_idx=st.next_idx.at[g, p].set(1).at[g, :, p].set(1),
            hb_due=st.hb_due.at[g, p].set(0),
            last_heard=st.last_heard.at[g, p].set(st.tick_no),
            elect_dl=st.elect_dl.at[g, p].set(
                st.tick_no + self.cfg.ELECT_MAX
            ),
            # Cross-replica columns about p (the regression fix): no
            # vote, prevote, match or ack of the OLD incarnation may
            # leak into the new one's ledger.
            votes=st.votes.at[g, p].set(False).at[g, :, p].set(False),
            pre_votes=st.pre_votes.at[g, p]
            .set(False)
            .at[g, :, p]
            .set(False),
            match_idx=st.match_idx.at[g, p].set(0).at[g, :, p].set(0),
            last_ack=st.last_ack.at[g, p]
            .set(0)
            .at[g, :, p]
            .set(st.tick_no),
            alive=st.alive.at[g, p].set(False),
        )
        # In-flight traffic of the old incarnation dies with it.
        self.inbox = self._mask_edges(self.inbox, g, p)
        self._delayed = [
            it
            for it in self._delayed
            if not (it[2][0] == g and p in (it[2][1], it[2][2]))
        ]

    # -- membership change (joint consensus) -------------------------------

    def _require_membership(self) -> None:
        if not self.cfg.membership_on:
            raise RuntimeError(
                "membership change requires EngineConfig.membership and "
                "the jnp reduction path (use_pallas=False) — the Pallas "
                "tally/commit kernels are mask-unaware"
            )

    def config_of(self, g: int, p: Optional[int] = None) -> Dict[str, Any]:
        """Replica (g, p)'s config view (the leader's when p is None):
        voter index sets, joint flag, epoch and the latest config
        entry's log index."""
        if p is None:
            p = self.leader_of(g)
            if p is None:
                raise RuntimeError(f"group {g} has no leader")
        st = self.np_state()
        bits_old = int(st["voters_old"][g, p])
        bits_new = int(st["voters_new"][g, p])
        unpack = lambda b: sorted(
            q for q in range(self.cfg.P) if (b >> q) & 1
        )
        return {
            "peer": int(p),
            "voters_old": unpack(bits_old),
            "voters_new": unpack(bits_new),
            "joint": bool(st["joint"][g, p]),
            "epoch": int(st["cfg_epoch"][g, p]),
            "cfg_idx": int(st["cfg_idx"][g, p]),
        }

    def add_learner(self, g: int, p: int) -> None:
        """AddServer step 1: (re)seat slot (g, p) as a NON-VOTING
        learner of group g — a fresh incarnation (stale vote/match
        state of any prior tenant cleared, see :meth:`reset_replica`)
        whose config view mirrors the leader's, so it knows it is not
        a voter and never campaigns.  Catch-up is the ordinary
        replication path: the leader snapshot-fast-forwards it and
        streams the tail; promotion (:meth:`begin_joint`) should wait
        for :meth:`learner_match` to close on the leader's last index
        so the joint phase never depends on a cold log."""
        self._require_membership()
        lead = self.leader_of(g)
        if lead is None:
            raise RuntimeError(f"add_learner: group {g} has no leader")
        if lead == p:
            raise ValueError(f"add_learner: ({g},{p}) is the leader")
        st = self.np_state()
        if ((int(st["voters_old"][g, lead]) | int(st["voters_new"][g, lead]))
                >> p) & 1:
            raise ValueError(
                f"add_learner: peer {p} is a voter of group {g}; remove "
                f"it from the config before reseating the slot"
            )
        self.reset_replica(g, p)
        st2 = self.state
        self.state = st2._replace(
            voters_old=st2.voters_old.at[g, p].set(
                st2.voters_old[g, lead]
            ),
            voters_new=st2.voters_new.at[g, p].set(
                st2.voters_new[g, lead]
            ),
            joint=st2.joint.at[g, p].set(st2.joint[g, lead]),
            cfg_epoch=st2.cfg_epoch.at[g, p].set(st2.cfg_epoch[g, lead]),
            cfg_idx=st2.cfg_idx.at[g, p].set(st2.cfg_idx[g, lead]),
            alive=st2.alive.at[g, p].set(True),
        )

    def learner_match(self, g: int, p: int) -> tuple:
        """(leader's match for p, leader's last index) — the catch-up
        gauge ``begin_joint`` callers poll before promoting."""
        lead = self.leader_of(g)
        if lead is None:
            raise RuntimeError(f"learner_match: group {g} has no leader")
        st = self.np_state()
        last = int(st["base"][g, lead] + st["log_len"][g, lead])
        return int(st["match_idx"][g, lead, p]), last

    def begin_joint(self, g: int, new_voters) -> int:
        """AddServer/RemoveServer step 2: append the C_old,new config
        entry at group g's leader (host surgery on the leader's row —
        the one entry the firehose cannot carry, since it must take
        effect ON APPEND).  From the next tick the leader replicates it
        like any entry; once it commits under BOTH quorums the tick
        auto-appends the C_new exit entry (core.py phase 5a-bis).
        Returns the joint entry's log index."""
        self._require_membership()
        new_voters = sorted(set(int(q) for q in new_voters))
        if not new_voters:
            raise ValueError("begin_joint: empty target voter set")
        if any(q < 0 or q >= self.cfg.P for q in new_voters):
            raise ValueError(
                f"begin_joint: voters {new_voters} out of range "
                f"0..{self.cfg.P - 1}"
            )
        lead = self.leader_of(g)
        if lead is None:
            raise RuntimeError(f"begin_joint: group {g} has no leader")
        st = self.np_state()
        if bool(st["joint"][g, lead]):
            raise RuntimeError(
                f"begin_joint: group {g} already has a config change in "
                f"flight (one at a time — Raft §6)"
            )
        mask = 0
        for q in new_voters:
            mask |= 1 << q
        if mask == int(st["voters_old"][g, lead]):
            raise ValueError("begin_joint: target equals current config")
        if self.cfg.L - 2 - self.cfg.E - int(st["log_len"][g, lead]) < 1:
            raise RuntimeError(
                f"begin_joint: group {g} leader log has no headroom"
            )
        idx = int(st["base"][g, lead] + st["log_len"][g, lead]) + 1
        term = int(st["term"][g, lead])
        s = self.state
        self.state = s._replace(
            log_term=s.log_term.at[g, lead, idx % self.cfg.L].set(term),
            log_len=s.log_len.at[g, lead].add(1),
            voters_new=s.voters_new.at[g, lead].set(mask),
            joint=s.joint.at[g, lead].set(True),
            cfg_epoch=s.cfg_epoch.at[g, lead].add(1),
            cfg_idx=s.cfg_idx.at[g, lead].set(idx),
        )
        return idx

    def seed_config(self, voters) -> None:
        """Bootstrap-time config: make ``voters`` (a peer index list)
        the voter set of EVERY group, leaving the remaining slots as
        dead spares a later :meth:`add_learner` can reseat.  Host
        surgery on a cluster that has not run yet — call before the
        first tick (replica replacement on a live group goes through
        ``add_learner``/``begin_joint``)."""
        self._require_membership()
        voters = sorted(set(int(q) for q in voters))
        if not voters or any(q < 0 or q >= self.cfg.P for q in voters):
            raise ValueError(f"seed_config: bad voter set {voters}")
        if int(np.asarray(self.state.tick_no)) != 0:
            raise RuntimeError("seed_config: cluster already ticked")
        mask = 0
        for q in voters:
            mask |= 1 << q
        spares = [q for q in range(self.cfg.P) if q not in voters]
        st = self.state
        alive = st.alive
        for q in spares:
            alive = alive.at[:, q].set(False)
        self.state = st._replace(
            voters_old=jnp.full_like(st.voters_old, mask),
            voters_new=jnp.full_like(st.voters_new, mask),
            alive=alive,
        )

    def reconfiguring(self) -> np.ndarray:
        """Per-group bool: a membership change is in flight — the group
        is in the joint phase, or its latest config entry has not yet
        committed.  Stateless read the wedge watchdog and placement
        health checks consult (a reconfiguring group's commit frontier
        may legitimately stall while it waits on BOTH quorums)."""
        st = self.np_state()
        return (
            st["joint"].any(axis=1)
            | (st["cfg_idx"].max(axis=1) > st["commit"].max(axis=1))
        )

    # -- Start() ----------------------------------------------------------

    def start(self, g: int, command: Any = None) -> None:
        """Queue a command for group g (the synthetic firehose feeds
        this in bulk)."""
        self.backlog[g] += 1
        # Drained by the tick's ingest path at INGEST ops/group/tick;
        # admission control above this layer (reply-queue caps, item 3)
        # is what bounds a sustained overload.
        self._pending_payloads[g].append(command)  # graftlint: disable=unbounded-queue

    def start_bulk(self, counts: np.ndarray) -> None:
        self.backlog += counts

    def start_run(self, g: int, frame: Any, rows: "np.ndarray") -> None:
        """Queue a contiguous RUN of firehose-frame rows for group
        ``g`` — ONE pending entry and one backlog bump of ``len(rows)``
        instead of a per-op append (the columnar serving path,
        engine/firehose.py).  ``rows`` are original frame row indices
        in submission order."""
        self.backlog[g] += len(rows)
        self._pending_payloads[g].append(PayloadRun(frame, rows))

    def _evict_rebound_range(self, g: int, lo: int, hi: int) -> None:
        """A fresh accept is about to bind slots ``[lo, hi]`` of group
        ``g``: every EXISTING binding overlapping ``[lo, ...)`` is
        stale — the log was truncated below it and those slots rewritten
        (an accept at start s0 means the leader's log ended at s0, so
        everything above is gone; slots beyond ``hi`` bound earlier are
        equally stale).  Per-op bindings sit at their own key; a slice
        keyed BELOW ``lo`` can straddle into the range, but its length
        is bounded by cfg.INGEST (one accept batch), so a bounded
        backward scan finds it.  A straddler's prefix below ``lo``
        survived the truncation and stays bound; the tail is evicted."""
        pay = self.payloads
        for idx in range(max(1, lo - self.cfg.INGEST + 1), hi + 1):
            old = pay.get((g, idx))
            if old is None:
                continue
            if isinstance(old, PayloadSlice):
                end = idx + old.count - 1
                if end < lo:
                    continue  # wholly below the rewrite: still valid
                if idx < lo:
                    # Straddler: keep the surviving prefix, evict the
                    # rewritten tail.
                    tail = PayloadSlice(old.frame, old.rows[lo - idx:])
                    old.rows = old.rows[: lo - idx]
                    if self.on_payload_evicted:
                        self.on_payload_evicted(tail)
                    continue
                pay.pop((g, idx))
                if self.on_payload_evicted:
                    self.on_payload_evicted(old)
            elif idx >= lo:
                pay.pop((g, idx))
                if self.on_payload_evicted:
                    self.on_payload_evicted(old)

    def _bind_accepted(
        self, g: int, k: int, s0: int, term: Optional[int]
    ) -> None:
        """Bind ``k`` accepted slots ``s0+1..s0+k`` of group ``g`` to
        pending payloads/runs, evicting whatever stale bindings the
        rewrite invalidated first (see :meth:`_evict_rebound_range` —
        without it, a slice bound before a truncation could later
        bulk-apply rows over slots that now hold different entries).

        The eviction scan only fires on a REBIND — an accept starting
        at or below the group's bind high-water mark (leader-churn
        truncation); steady-state accepts pay one dict probe."""
        # One accept batch can never exceed the kernel's ingest lane
        # width; a larger k means the accept-count column was
        # corrupted, and binding it would smear payloads across slots
        # the kernel never accepted.
        assert k <= self.cfg.INGEST, (
            f"accept batch k={k} exceeds cfg.INGEST={self.cfg.INGEST} "
            f"for group {g}"
        )
        lo, hi = s0 + 1, s0 + k
        mb = self._max_bound.get(g, 0)
        if self.payloads and lo <= mb:
            self._evict_rebound_range(g, lo, hi)
        if hi > mb:
            self._max_bound[g] = hi
        pend = self._pending_payloads.get(g)
        if not pend:
            return
        off = 0
        while off < k and pend:
            head = pend[0]
            slot = (g, s0 + 1 + off)
            if isinstance(head, PayloadRun):
                # One bound entry covers a whole run prefix —
                # per-slice, not per-op.
                take = min(head.remaining, k - off)
                self.payloads[slot] = head.take(take)
                if head.remaining == 0:
                    pend.pop(0)
                if term is not None:
                    for j in range(take):
                        self.on_payload_bound(slot[0], slot[1] + j, term)
                off += take
            else:
                self.payloads[slot] = pend.pop(0)
                if term is not None:
                    self.on_payload_bound(slot[0], slot[1], term)
                off += 1

    # -- tick loop --------------------------------------------------------

    def step(self, n: int = 1) -> Dict[str, Any]:
        """Advance ``n`` ticks.  Multi-tick calls on a pipeline-enabled
        driver run the fused device scan (engine/pipeline.py — one host
        sync per call instead of one per tick); everything else —
        single ticks, mesh drivers, reorder chaos in flight,
        ``MRT_ENGINE_PIPELINE=0`` — takes the serial per-tick loop.
        Both paths are bit-identical by contract
        (tests/test_engine_pipeline.py).

        Synchronous callers (admin_sync, checkpoint replay, tests) may
        land here while the serving loop still has dispatched batches
        in flight: drain them first, in dispatch order — safe because
        ``step`` already must run on the owning thread, and the serving
        loop's ``_pump_done`` ignores batches completed from under it."""
        while self._inflight:
            p = self._inflight[0]
            self.complete_ticks(p, p.fetch())
        if n > 1 and self.fused_eligible():
            pending = self.dispatch_ticks(n)
            return self.complete_ticks(pending, pending.fetch())
        return self._step_serial(n)

    def fused_eligible(self) -> bool:
        """True when the fused scan path may run: pipeline enabled, no
        mesh tick (its scalar metrics arrive as per-device lanes), and
        no reorder chaos active or held (``_apply_reorder`` rewrites
        the mailbox on host between ticks — inherently unfusable)."""
        return (
            self._pipeline_on
            and self._mesh_tick is None
            and self.reorder_prob == 0.0
            and not self._delayed
        )

    def _step_serial(self, n: int = 1) -> Dict[str, Any]:
        assert not self._inflight, (
            "serial step with fused tick batches in flight — complete "
            "them first, or the two tick streams interleave"
        )
        cfg = self.cfg
        self.metrics.inc("ticks", n)
        for _ in range(n):
            self.tick += 1
            t_wall = time.perf_counter() if self.tracer else 0.0
            tick_key = jax.random.fold_in(self.key, self.tick)
            have_backlog = bool(self.backlog.any())
            new_cmds = jnp.asarray(
                np.minimum(self.backlog, cfg.INGEST), jnp.int32
            ) if have_backlog else jnp.zeros(cfg.G, jnp.int32)
            if self._mesh_tick is not None:
                state, outbox, metrics = self._mesh_tick(
                    self.state, self.inbox, new_cmds, tick_key
                )
                # Scalar metrics arrive as per-device lanes (the
                # zero-collective contract, engine/mesh.py): sum to the
                # scalars the host-side consumers expect.
                metrics = dict(metrics)
                for k in SCALAR_METRIC_KEYS:
                    red = jnp.max if k == "max_term" else jnp.sum
                    metrics[k] = red(metrics[k])
            else:
                state, outbox, metrics = tick(
                    cfg, self.state, self.inbox, new_cmds, tick_key
                )
            if self.drop_prob > 0.0:
                outbox = apply_faults(
                    outbox,
                    jax.random.fold_in(tick_key, 0xFA),
                    jnp.float32(self.drop_prob),
                    cfg,
                )
            if not self.edge_up.all():
                outbox = self._mask_partitions(outbox)
            if self.reorder_prob > 0.0 or self._delayed:
                outbox = self._apply_reorder(outbox)
            self.state, self.inbox = state, outbox
            if have_backlog:
                # Host sync only while commands are in flight.
                accepted = np.asarray(metrics["accepted"])
                starts = np.asarray(metrics["start_index"])
                terms = (
                    np.asarray(metrics["accept_term"])
                    if self.on_payload_bound else None
                )
                for g in np.nonzero(accepted)[0]:
                    k = int(accepted[g])
                    self.backlog[g] -= k
                    self._bind_accepted(
                        int(g), k, int(starts[g]),
                        int(terms[g]) if terms is not None else None,
                    )
            # Accumulate on device; converted lazily by readers.
            self._commits_dev = (
                getattr(self, "_commits_dev", jnp.int32(0)) + metrics["commits"]
            )
            self.last_metrics = metrics
            if self.tracer:
                commits = int(metrics["commits"])  # forces the sync
                self.metrics.observe(
                    "tick_wall_s", time.perf_counter() - t_wall
                )
                now_us = time.perf_counter() * 1e6
                self.tracer.span(
                    "tick",
                    t_wall * 1e6,
                    now_us - t_wall * 1e6,
                    track="engine",
                    tick=self.tick,
                    commits=commits,
                    leaders=int(metrics["leaders"]),
                )
                self.tracer.counter(
                    "consensus", now_us,
                    {"commits": commits, "backlog": int(self.backlog.sum())},
                )
        return self.last_metrics

    # -- fused pipeline (engine/pipeline.py) ------------------------------

    def dispatch_ticks(self, n: int):
        """Dispatch a fused ``n``-tick batch to the device WITHOUT
        waiting for it: JAX async dispatch makes the returned arrays
        futures, so this only pays trace/enqueue cost on the calling
        (scheduler-loop) thread.  Requires :meth:`fused_eligible`.

        The host tick counter and state/inbox advance immediately —
        payload binding and backlog bookkeeping are deferred to
        :meth:`complete_ticks` once the stacked metrics are fetched
        (``PendingTicks.fetch``, safe off-thread)."""
        from .pipeline import PendingTicks, step_ticks

        cfg = self.cfg
        t_dispatch = time.perf_counter()
        self.metrics.inc("ticks", n)
        tick0 = self.tick
        bl = jnp.asarray(
            np.minimum(self.backlog, np.int64(2**31 - 1)).astype(np.int32)
        )
        for p in self._inflight:
            # Batches already dispatched will consume part of the host
            # backlog when they complete; the device must not ingest
            # those commands again (the depth ≥ 2 double-ingest hazard).
            # accepts_dev never left the device, so this stays async.
            bl = jnp.maximum(bl - p.accepts_dev, 0)
        with_drop = self.drop_prob > 0.0
        with_edges = not bool(self.edge_up.all())
        if with_edges:
            if self._edge_dev is None:
                # copy=True: see _mask_partitions.
                self._edge_dev = jnp.array(self.edge_up, copy=True)
            edge_mask = self._edge_dev
        else:
            edge_mask = jnp.zeros((), jnp.bool_)  # static-dead operand
        state, inbox, _bl_left, rec = step_ticks(
            cfg, self.state, self.inbox, n, with_drop, with_edges,
            bl, jnp.float32(self.drop_prob), edge_mask,
            jnp.int32(tick0), self.key,
        )
        self.state, self.inbox = state, inbox
        self.tick = tick0 + n
        pending = PendingTicks(
            n=n, tick0=tick0, rec=rec,
            accepts_dev=jnp.sum(rec["accepted"], axis=0),
            t_dispatch=t_dispatch,
        )
        self._inflight.append(pending)  # graftlint: disable=unbounded-queue
        return pending

    def complete_ticks(self, pending, host_rec) -> Dict[str, Any]:
        """Fold a fetched batch back into host bookkeeping: per-tick
        backlog decrements and payload binding replayed in tick order
        from the stacked record, the commit accumulator, last_metrics,
        and (tracer mode) the buffered per-tick spans — one host sync
        per pump where the serial loop paid one per tick.  Must run on
        the owning (scheduler) thread, in dispatch order."""
        assert self._inflight and self._inflight[0] is pending, (
            "complete_ticks out of dispatch order"
        )
        self._inflight.pop(0)
        accepted = host_rec["accepted"]  # i32[n, G]
        starts = host_rec["start_index"]
        terms = host_rec["accept_term"] if self.on_payload_bound else None
        # np.nonzero on [n, G] is row-major: tick-major, group-minor —
        # exactly the serial loop's binding order.
        for i, g in zip(*np.nonzero(accepted)):
            k = int(accepted[i, g])
            self.backlog[g] -= k
            self._bind_accepted(
                int(g), k, int(starts[i, g]),
                int(terms[i, g]) if terms is not None else None,
            )
        self._commits_dev = (
            getattr(self, "_commits_dev", 0) + int(host_rec["commits"].sum())
        )
        self.last_metrics = {k: v[-1] for k, v in host_rec.items()}
        if self.tracer:
            self._emit_tick_spans(pending, host_rec)
        return self.last_metrics

    def _emit_tick_spans(self, pending, rec) -> None:
        """Tracer spans for a completed fused batch: the per-tick wall
        clock no longer exists (ticks fused on device), so the batch
        wall is spread evenly across its ticks.  Commit/leader fields
        come from the stacked record — no extra device syncs."""
        n = pending.n
        now = time.perf_counter()
        per = max(now - pending.t_dispatch, 1e-9) / n
        t = pending.t_dispatch
        commits_total = int(rec["commits"].sum())
        for i in range(n):
            self.metrics.observe("tick_wall_s", per)
            self.tracer.span(
                "tick",
                t * 1e6,
                per * 1e6,
                track="engine",
                tick=pending.tick0 + 1 + i,
                commits=int(rec["commits"][i]),
                leaders=int(rec["leaders"][i]),
            )
            t += per
        self.tracer.counter(
            "consensus", now * 1e6,
            {"commits": commits_total, "backlog": int(self.backlog.sum())},
        )

    @property
    def commits_total(self) -> int:
        return int(getattr(self, "_commits_dev", 0)) + self.total_commits

    def run_until_quiet_leaders(self, max_ticks: int = 500) -> bool:
        """Advance until every group has exactly one live leader."""
        stride = 5  # check every few ticks: readbacks are host syncs
        for _ in range(0, max_ticks, stride):
            self.step(stride)
            if self.leaders_per_group().min() >= 1:
                if self.leaders_at_max_term_per_group().max() <= 1:
                    return True
        return False

    # -- checkpoint / resume ----------------------------------------------
    #
    # Whole-engine suspend/resume: the batched analog of the reference's
    # Persister (reference: raft/persister.go:57-64 atomic pair save),
    # scaled to the world where one host owns every replica of every
    # group.  Because the checkpoint captures the ENTIRE cluster
    # atomically at a tick boundary (state + in-flight mailbox + host
    # bookkeeping), restoring it is equivalent to pausing and resuming
    # the world — consistent by construction, no per-replica recovery
    # protocol needed.  This is the TPU-preemption recovery path;
    # *individual* crash fidelity stays with restart_replica().

    # v2: EngineState gained pre_votes/last_heard (PreVote support);
    # Mailbox gained vr_pre/vp_pre.
    # v3: EngineState gained last_ack (check-quorum stepdown).
    # v4: EngineState gained voters_old/voters_new/joint/cfg_epoch/
    # cfg_idx and Mailbox gained the ar_cfg_* lanes (joint-consensus
    # membership change) — config state rides the generic _asdict()
    # path, so an in-flight reconfig survives checkpoint/restore.
    CKPT_VERSION = 4

    def save(self, path: str, extra: Optional[Dict[str, Any]] = None) -> str:
        """Atomically write a full checkpoint.  ``extra`` carries
        service-level state (e.g. ``FrontierService.state_dict()``) so
        engine and services checkpoint at the same tick boundary."""
        if self._inflight:
            # state/inbox already reflect the dispatched batches but
            # backlog/payload bookkeeping does not — a checkpoint here
            # would tear the tick boundary.  The durable serving loop
            # drains the pipeline before checkpointing (and pins the
            # pipeline depth to 1); see ARCHITECTURE §20.
            raise RuntimeError(
                "save() with fused tick batches in flight — drain the "
                "pipeline (complete_ticks) before checkpointing"
            )
        blob = {
            "version": self.CKPT_VERSION,
            "mesh_devices": (
                int(self.mesh.devices.size) if self.mesh is not None else 0
            ),
            "cfg": self.cfg,
            "state": {
                k: np.asarray(v) for k, v in self.state._asdict().items()
            },
            "inbox": {
                k: np.asarray(v) for k, v in self.inbox._asdict().items()
            },
            "tick": self.tick,
            "key": np.asarray(self.key),
            "backlog": self.backlog,
            "payloads": self.payloads,
            "pending_payloads": dict(self._pending_payloads),
            "edge_up": self.edge_up,
            "replica_conn": self.replica_conn,
            "drop_prob": self.drop_prob,
            "reorder": (self.reorder_prob, self.reorder_min, self.reorder_max),
            # The reorder RNG's position: a resumed run must draw the
            # same picks/delays as the uninterrupted one (determinism
            # is the sim's debugging contract).
            "np_rng": self._np_rng.bit_generator.state,
            "delayed": self._delayed,
            "commits_total": self.commits_total,
            "extra": extra or {},
        }
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(blob, f, protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            # Intentional loop-thread sync point: checkpoint atomicity
            # (the durable server truncates its WAL right after this
            # returns, so the checkpoint must hit the platter first).
            os.fsync(f.fileno())  # graftlint: disable=blocking-in-callback
        os.replace(tmp, path)  # atomic: a crash mid-save keeps the old one
        # Make the rename itself durable: the durable-server protocol
        # truncates its WAL right after this call, and on power loss
        # POSIX gives no cross-file ordering — the truncation must not
        # become durable while the checkpoint rename does not.
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)  # graftlint: disable=blocking-in-callback
        finally:
            os.close(dfd)
        return path

    @classmethod
    def restore(cls, path: str, mesh=None) -> "EngineDriver":
        """Rebuild a driver from :meth:`save`.  The returned driver
        continues from the exact saved tick; the checkpoint's ``extra``
        dict is available as ``driver.restored_extra``.

        A checkpoint taken from a mesh driver must be restored with a
        ``mesh`` (same device count) — silently coming back
        single-device would drop the sharding/zero-collective
        guarantees and concentrate the full state on one chip."""
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if blob.get("version") != cls.CKPT_VERSION:
            raise ValueError(
                f"checkpoint version {blob.get('version')} != {cls.CKPT_VERSION}"
            )
        saved_mesh = blob.get("mesh_devices", 0)
        if saved_mesh and mesh is None:
            raise ValueError(
                f"checkpoint was taken from a {saved_mesh}-device mesh "
                f"driver; pass restore(..., mesh=) with a "
                f"{saved_mesh}-device mesh to re-shard it"
            )
        if saved_mesh and mesh is not None and (
            int(mesh.devices.size) != saved_mesh
        ):
            # Silently concentrating N× the per-chip state on a smaller
            # mesh is an OOM/perf cliff, not a config the operator
            # asked for — loud beats lucky.
            raise ValueError(
                f"checkpoint was taken on {saved_mesh} devices but "
                f"restore got a {int(mesh.devices.size)}-device mesh"
            )
        d = object.__new__(cls)  # skip __init__: no throwaway device state
        d._init_host(blob["cfg"], seed=0)
        # jnp.array(..., copy=True), NOT jnp.asarray: the CPU backend
        # may zero-copy a numpy array, leaving the device buffer
        # aliased to the unpickled blob — and the tick DONATES its
        # state/inbox inputs, so the first step after restore would
        # write through into non-jax-owned memory (observed as a
        # SIGSEGV inside the first post-restore dispatch when the
        # executable comes from the persistent compilation cache).
        d.state = EngineState(
            **{k: jnp.array(v, copy=True) for k, v in blob["state"].items()}
        )
        d.inbox = Mailbox(
            **{k: jnp.array(v, copy=True) for k, v in blob["inbox"].items()}
        )
        if mesh is not None:
            from .mesh import make_sharded_tick, shard_arrays

            d.mesh = mesh
            d.state = shard_arrays(d.cfg, mesh, d.state)
            d.inbox = shard_arrays(d.cfg, mesh, d.inbox)
            d._mesh_tick = make_sharded_tick(d.cfg, mesh)
        d.tick = blob["tick"]
        d.key = jnp.array(blob["key"], copy=True)
        d.backlog = blob["backlog"]
        d.payloads = blob["payloads"]
        d._pending_payloads = defaultdict(list, blob["pending_payloads"])
        # Rebuild the bind high-water marks from the restored bindings
        # (a zeroed mark would skip the rebind eviction scan and let a
        # post-restore truncation phantom-apply a stale slice).
        d._max_bound = {}
        for (g, idx), p in d.payloads.items():
            end = idx + (p.count - 1 if isinstance(p, PayloadSlice) else 0)
            if end > d._max_bound.get(g, 0):
                d._max_bound[g] = end
        d.edge_up = blob["edge_up"]
        d.replica_conn = blob["replica_conn"]
        d._edge_dev = None
        d.drop_prob = blob["drop_prob"]
        d.reorder_prob, d.reorder_min, d.reorder_max = blob["reorder"]
        d._np_rng.bit_generator.state = blob["np_rng"]
        d._delayed = blob["delayed"]
        d.total_commits = blob["commits_total"]
        d.restored_extra = blob["extra"]
        return d

    # -- inspection (host readbacks; test/debug path) ---------------------

    def np_state(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.state._asdict().items()}

    def leaders_per_group(self) -> np.ndarray:
        st = self.np_state()
        return (
            ((st["role"] == LEADER) & st["alive"]).sum(axis=1)
        )

    def leaders_at_max_term_per_group(self) -> np.ndarray:
        st = self.np_state()
        lead = (st["role"] == LEADER) & st["alive"]
        # Leaders are unique per *term*; count leaders in the max term.
        max_term = np.where(lead, st["term"], -1).max(axis=1, keepdims=True)
        return (lead & (st["term"] == max_term)).sum(axis=1)

    def leader_of(self, g: int) -> Optional[int]:
        st = self.np_state()
        lead = np.nonzero((st["role"][g] == LEADER) & st["alive"][g])[0]
        if len(lead) == 0:
            return None
        terms = st["term"][g][lead]
        return int(lead[np.argmax(terms)])

    def log_terms_of(
        self, g: int, p: int, st: Optional[Dict[str, np.ndarray]] = None
    ) -> Dict[int, int]:
        """Absolute index -> term for replica (g, p)'s ring window.

        Pass a pre-read ``st`` (from :meth:`np_state`) when reading many
        replicas — each call otherwise syncs the full state to host."""
        if st is None:
            st = self.np_state()
        base, ln = int(st["base"][g, p]), int(st["log_len"][g, p])
        ring = st["log_term"][g, p]
        return {
            i: int(ring[i % self.cfg.L]) for i in range(base + 1, base + ln + 1)
        }

    def check_log_matching(self, g: int) -> None:
        """Safety: all replicas agree on terms up to their common window
        below min(commit) (Log Matching + State Machine Safety)."""
        st = self.np_state()
        commits = st["commit"][g]
        floor = int(min(commits))
        views = [self.log_terms_of(g, p, st) for p in range(self.cfg.P)]
        bases = st["base"][g]
        for i in range(int(max(bases)) + 1, floor + 1):
            terms = {v[i] for v in views if i in v}
            assert len(terms) <= 1, (
                f"group {g}: index {i} has conflicting committed terms {terms}"
            )
