"""Shared scaffolding for services on the batched engine.

Both batched services (:class:`~multiraft_tpu.engine.kv.BatchedKV`,
:class:`~multiraft_tpu.engine.shardkv.BatchedShardKV`) follow the same
loop: advance the device tick, pop committed ``(group, index)`` payload
bindings in order and apply them, and periodically fail tickets whose
binding was truncated by a leader change (the batched analog of kvraft
waiters resolving ErrWrongLeader on term change,
reference: kvraft/server.go:98-128).  This base class owns that loop so
the sweep condition and eviction contract live in exactly one place.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .host import EngineDriver, PayloadSlice

__all__ = ["FrontierService"]


class FrontierService:
    """Applies the committed frontier of an :class:`EngineDriver` to a
    host-side state machine.  Subclasses implement ``_apply`` (one
    committed payload) and ``_on_evicted`` (a payload that lost its log
    slot and can never commit as bound), and may hook ``_post_pump``
    (runs after each frontier sweep — orchestration goes here)."""

    ORPHAN_SWEEP_TICKS = 64

    def __init__(self, driver: EngineDriver) -> None:
        self.driver = driver
        self.applied_upto = [0] * driver.cfg.G
        driver.on_payload_evicted = self._on_evicted
        self._sweep_countdown = self.ORPHAN_SWEEP_TICKS
        # Entries applied by the LAST pump's sweep — the serving pump
        # loops read it as their work-pending signal (adaptive pump
        # cadence: hot while traffic flows, idle interval otherwise).
        self.last_applied = 0
        # Split-group mode (engine/split.py): applied payloads are KEPT
        # so a lagging remote peer's resend can still ship them; the
        # peering GCs below the ring floor instead.  Default False: the
        # pop keeps host memory bounded under a sustained firehose.
        self.retain_payloads = False

    # -- subclass hooks ----------------------------------------------------

    def _apply(self, g: int, idx: int, payload: Any, now: int) -> None:
        raise NotImplementedError

    def _apply_slice(self, g: int, idx: int, sl: PayloadSlice, now: int) -> None:
        """Apply one bound firehose slice (``sl.count`` consecutive
        committed indices starting at ``idx``).  Services that accept
        firehose frames override with a bulk apply; the default keeps
        non-firehose services correct if a slice ever reaches them."""
        raise NotImplementedError(
            f"{type(self).__name__} does not accept firehose slices"
        )

    def _on_evicted(self, payload: Any) -> None:
        raise NotImplementedError

    def _post_pump(self) -> None:
        pass

    def _pre_sweep(self) -> None:
        """Runs between the device step and the apply sweep (split mode
        raises the device's host-paced applied frontier here)."""
        pass

    # -- checkpoint hooks (pair with EngineDriver.save/restore) -----------

    def state_dict(self) -> Dict[str, Any]:
        """Service state to checkpoint alongside the engine — pass as
        ``driver.save(path, extra=svc.state_dict())`` so both snapshot
        the same tick boundary.  Subclasses extend."""
        return {"applied_upto": list(self.applied_upto)}

    def load_state_dict(self, blob: Dict[str, Any]) -> None:
        self.applied_upto = list(blob["applied_upto"])

    # -- the loop ----------------------------------------------------------

    def pump(self, n_ticks: int = 1) -> None:
        """Advance the engine and apply the committed frontier
        (DeferredConsensus.pump)."""
        self.driver.step(n_ticks)
        self.after_step(n_ticks)

    def after_step(self, n_ticks: int = 1) -> None:
        """The host half of :meth:`pump`: everything after the engine
        advance — frontier sweep, apply, orphan sweep.  The pipelined
        serving loop calls this from ``complete_ticks`` handoff (the
        engine advance happened on dispatch), the synchronous path via
        :meth:`pump`.  Requires ``driver.last_metrics`` to reflect the
        ticks being accounted for."""
        self._pre_sweep()
        commit = np.asarray(self.driver.last_metrics["commit_index"])
        now = self.driver.tick
        applied = 0
        for g in range(self.driver.cfg.G):
            upto = int(commit[g])
            while self.applied_upto[g] < upto:
                idx = self.applied_upto[g] + 1
                # pop: an applied payload is never needed again (host
                # memory stays bounded under a sustained firehose) —
                # unless split-group resends still need it (see
                # retain_payloads above).
                if self.retain_payloads:
                    payload = self.driver.payloads.get((g, idx))
                else:
                    payload = self.driver.payloads.pop((g, idx), None)
                if isinstance(payload, PayloadSlice):
                    # Bulk path: the slice covers consecutive indices;
                    # apply the committed prefix whole and re-key any
                    # uncommitted tail at the split point.
                    assert not self.retain_payloads, (
                        "firehose slices are pop-applied; split-group "
                        "services (retain_payloads) have no firehose "
                        "surface"
                    )
                    avail = upto - idx + 1
                    if payload.count > avail:
                        tail_key = (g, idx + avail)
                        stale = self.driver.payloads.get(tail_key)
                        if stale is not None:
                            self._on_evicted(stale)
                        self.driver.payloads[tail_key] = payload
                        payload = payload.split_head(avail)
                    self._apply_slice(g, idx, payload, now)
                    self.applied_upto[g] = idx + payload.count - 1
                    applied += payload.count
                else:
                    self._apply(g, idx, payload, now)
                    self.applied_upto[g] = idx
                    applied += 1
        self.last_applied = applied
        self._post_pump()
        # Periodically fail bindings orphaned by log truncation (a
        # leader change can strand tail bindings that no future accept
        # will overwrite if the group goes quiet).
        self._sweep_countdown -= n_ticks
        if self._sweep_countdown <= 0:
            self._sweep_countdown = self.ORPHAN_SWEEP_TICKS
            self.sweep_orphans()

    def sweep_orphans(self) -> int:
        """Fail tickets whose bound (group, index) log entry no longer
        exists in the current leader's log — it was truncated by a
        leader change and can never commit as bound.  Returns the number
        of tickets failed.

        Slice-aware: a firehose slice wholly beyond the log end is
        evicted whole; one straddling it is truncated (the surviving
        prefix stays bound).  Stale bindings shadowed below the applied
        frontier (their slots were rewritten and applied through a
        fresher binding) are failed too, so their rows resolve promptly
        instead of waiting out the frame deadline."""
        if not self.driver.payloads:
            return 0
        st = self.driver.np_state()
        failed = 0
        last_cache: Dict[int, Optional[int]] = {}
        for (g, idx) in list(self.driver.payloads.keys()):
            if g not in last_cache:
                p = self.driver.leader_of(g)
                last_cache[g] = (
                    None
                    if p is None
                    else int(st["base"][g, p] + st["log_len"][g, p])
                )
            last = last_cache[g]
            payload = self.driver.payloads.get((g, idx))
            count = payload.count if isinstance(payload, PayloadSlice) else 1
            if (
                not self.retain_payloads
                and idx + count - 1 <= self.applied_upto[g]
            ):
                # Stale: the frontier passed this whole binding via a
                # fresher covering binding — these rows lost their
                # slots and can never apply as bound.  (Split-group
                # mode RETAINS applied payloads for peer resends —
                # below-frontier there is the normal state, not stale.)
                self._on_evicted(self.driver.payloads.pop((g, idx)))
                failed += 1
                continue
            if last is None:
                continue
            if idx > last:
                self._on_evicted(self.driver.payloads.pop((g, idx)))
                failed += 1
            elif idx + count - 1 > last:
                # Straddles the log end: fail the truncated tail only.
                keep = last - idx + 1
                tail = PayloadSlice(payload.frame, payload.rows[keep:])
                payload.rows = payload.rows[:keep]
                self._on_evicted(tail)
                failed += 1
        return failed
