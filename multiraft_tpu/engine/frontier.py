"""Shared scaffolding for services on the batched engine.

Both batched services (:class:`~multiraft_tpu.engine.kv.BatchedKV`,
:class:`~multiraft_tpu.engine.shardkv.BatchedShardKV`) follow the same
loop: advance the device tick, pop committed ``(group, index)`` payload
bindings in order and apply them, and periodically fail tickets whose
binding was truncated by a leader change (the batched analog of kvraft
waiters resolving ErrWrongLeader on term change,
reference: kvraft/server.go:98-128).  This base class owns that loop so
the sweep condition and eviction contract live in exactly one place.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .host import EngineDriver

__all__ = ["FrontierService"]


class FrontierService:
    """Applies the committed frontier of an :class:`EngineDriver` to a
    host-side state machine.  Subclasses implement ``_apply`` (one
    committed payload) and ``_on_evicted`` (a payload that lost its log
    slot and can never commit as bound), and may hook ``_post_pump``
    (runs after each frontier sweep — orchestration goes here)."""

    ORPHAN_SWEEP_TICKS = 64

    def __init__(self, driver: EngineDriver) -> None:
        self.driver = driver
        self.applied_upto = [0] * driver.cfg.G
        driver.on_payload_evicted = self._on_evicted
        self._sweep_countdown = self.ORPHAN_SWEEP_TICKS
        # Entries applied by the LAST pump's sweep — the serving pump
        # loops read it as their work-pending signal (adaptive pump
        # cadence: hot while traffic flows, idle interval otherwise).
        self.last_applied = 0
        # Split-group mode (engine/split.py): applied payloads are KEPT
        # so a lagging remote peer's resend can still ship them; the
        # peering GCs below the ring floor instead.  Default False: the
        # pop keeps host memory bounded under a sustained firehose.
        self.retain_payloads = False

    # -- subclass hooks ----------------------------------------------------

    def _apply(self, g: int, idx: int, payload: Any, now: int) -> None:
        raise NotImplementedError

    def _on_evicted(self, payload: Any) -> None:
        raise NotImplementedError

    def _post_pump(self) -> None:
        pass

    def _pre_sweep(self) -> None:
        """Runs between the device step and the apply sweep (split mode
        raises the device's host-paced applied frontier here)."""
        pass

    # -- checkpoint hooks (pair with EngineDriver.save/restore) -----------

    def state_dict(self) -> Dict[str, Any]:
        """Service state to checkpoint alongside the engine — pass as
        ``driver.save(path, extra=svc.state_dict())`` so both snapshot
        the same tick boundary.  Subclasses extend."""
        return {"applied_upto": list(self.applied_upto)}

    def load_state_dict(self, blob: Dict[str, Any]) -> None:
        self.applied_upto = list(blob["applied_upto"])

    # -- the loop ----------------------------------------------------------

    def pump(self, n_ticks: int = 1) -> None:
        """Advance the engine and apply the committed frontier
        (DeferredConsensus.pump)."""
        self.driver.step(n_ticks)
        self._pre_sweep()
        commit = np.asarray(self.driver.last_metrics["commit_index"])
        now = self.driver.tick
        applied = 0
        for g in range(self.driver.cfg.G):
            upto = int(commit[g])
            while self.applied_upto[g] < upto:
                idx = self.applied_upto[g] + 1
                # pop: an applied payload is never needed again (host
                # memory stays bounded under a sustained firehose) —
                # unless split-group resends still need it (see
                # retain_payloads above).
                if self.retain_payloads:
                    payload = self.driver.payloads.get((g, idx))
                else:
                    payload = self.driver.payloads.pop((g, idx), None)
                self._apply(g, idx, payload, now)
                self.applied_upto[g] = idx
                applied += 1
        self.last_applied = applied
        self._post_pump()
        # Periodically fail bindings orphaned by log truncation (a
        # leader change can strand tail bindings that no future accept
        # will overwrite if the group goes quiet).
        self._sweep_countdown -= n_ticks
        if self._sweep_countdown <= 0:
            self._sweep_countdown = self.ORPHAN_SWEEP_TICKS
            self.sweep_orphans()

    def sweep_orphans(self) -> int:
        """Fail tickets whose bound (group, index) log entry no longer
        exists in the current leader's log — it was truncated by a
        leader change and can never commit as bound.  Returns the number
        of tickets failed."""
        if not self.driver.payloads:
            return 0
        st = self.driver.np_state()
        failed = 0
        last_cache: Dict[int, Optional[int]] = {}
        for (g, idx) in list(self.driver.payloads.keys()):
            if g not in last_cache:
                p = self.driver.leader_of(g)
                last_cache[g] = (
                    None
                    if p is None
                    else int(st["base"][g, p] + st["log_len"][g, p])
                )
            last = last_cache[g]
            if last is not None and idx > last:
                payload = self.driver.payloads.pop((g, idx))
                self._on_evicted(payload)
                failed += 1
        return failed
