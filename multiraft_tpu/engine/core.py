"""The batched TPU consensus engine — multi-Raft as one jitted tick.

This is the TPU-native inversion of the reference's runtime: instead of
3+2(n−1) goroutines per Raft instance (reference: raft/raft.go:51-87),
*every replica of every group* lives in struct-of-arrays state tensors
with a leading ``(G, P)`` = (groups, peers) axis, and one pure
``tick(state, inbox, ...) → (state, outbox, metrics)`` function advances
them all synchronously.  RPCs are dense per-edge mailboxes
``[G, src, dst]``; the labrpc fault model becomes masks (drop,
partition) applied between outbox and inbox (SURVEY §2.2, §5.8).

Per-phase mapping to the reference:

* vote request/reply handling  — raft/raft_election.go:4-77
* append request handling incl. conflict backoff
                               — raft/raft_append_entry.go:108-162
* reply processing + quorum commit advance (the north-star kernel)
                               — raft/raft_append_entry.go:66-105
* snapshot fast-forward        — raft/raft_snapshot.go:15-54 (the
  ``snap`` flag compresses InstallSnapshot into the append channel;
  snapshot *data* lives host-side keyed by (group, index))

Deliberate divergences (documented):

* Conflict backoff jumps straight to ``min(prev, commit+1)`` — the
  follower's committed prefix provably matches the leader, so
  repositioning takes O(1) round trips instead of the reference's
  term-scan (raft/raft_append_entry.go:136-143); data catch-up then
  streams at ``E`` entries per message.
* Election timeouts are integer ticks with per-replica jitter drawn
  from a counter-based PRNG (replaces the reference's wall-clock reseed
  quirk, raft/raft.go:46-50).
* Logs are fixed-capacity rings with ``base`` rebase; compaction
  advances ``base`` over the applied prefix automatically (the
  service-driven Snapshot() of the reference becomes a frontier the
  host reads).

Sharding: every tensor is independent along G, so the whole engine
shards over a ``Mesh`` 'groups' axis with zero collectives (use
``jax.shard_map`` so the steady-state fast-path conds evaluate
per-device — under plain GSPMD jit their global predicates lower to
scalar all-reduces; see ``__graft_entry__.dryrun_multichip``) — consensus
*within* a group never crosses a shard boundary.  (Cross-host traffic
only appears when a logical group spans hosts, which the transport
layer handles, not the kernel.)
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..utils.knobs import knob_bool

__all__ = [
    "EngineConfig", "EngineState", "Mailbox", "init_state",
    "empty_mailbox", "tick", "METRIC_KEYS", "SCALAR_METRIC_KEYS",
]

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2


def prevote_default() -> bool:
    """PreVote election mode, ON unless ``MRT_PREVOTE=0`` (kill switch).
    Read at EngineConfig construction, so the legacy arm of the CI A/B
    matrix flips it per-process without touching call sites."""
    return knob_bool("MRT_PREVOTE")


def check_quorum_default() -> bool:
    """Check-quorum leader self-demotion, ON unless
    ``MRT_CHECK_QUORUM=0`` (kill switch, paired with MRT_PREVOTE)."""
    return knob_bool("MRT_CHECK_QUORUM")


def membership_default() -> bool:
    """Joint-consensus membership change, ON unless ``MRT_MEMBERSHIP=0``
    (kill switch).  With every group at its full static peer set the
    masked dual-quorum reductions are value-identical to the legacy
    single-quorum ones (see the math note on EngineConfig.membership),
    so default-on changes no behavior until a config entry lands."""
    return knob_bool("MRT_MEMBERSHIP")

# The tick's metrics schema — single source of truth for the mesh
# path's out_specs (engine/mesh.py) and the host's per-device scalar
# reduction (engine/host.py).  SCALAR keys are cluster-wide scalars
# (per-device lanes under a mesh); the rest are per-group [G] vectors.
SCALAR_METRIC_KEYS = ("commits", "leaders", "max_term")
METRIC_KEYS = SCALAR_METRIC_KEYS + (
    "accepted", "start_index", "accept_term", "commit_index",
)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static shape/timing parameters (hashable: passed as a jit static).

    Timing is in ticks; with the reference's wall-clock mapping of
    10 ms/tick these defaults reproduce its 90 ms heartbeat and
    300–600 ms election window (reference: raft/raft.go:42-50).  The
    bench shrinks the tick period to whatever the chip sustains.
    """

    G: int = 8  # groups
    P: int = 3  # peers per group
    L: int = 64  # log ring capacity per replica
    E: int = 8  # max entries per append message
    INGEST: int = 8  # max Start() commands accepted per group per tick
    HB_TICKS: int = 9
    ELECT_MIN: int = 30
    ELECT_MAX: int = 60
    # Pallas kernels for vote tally + quorum commit (the north-star
    # ops); interpret=True runs them under the Pallas interpreter on
    # non-TPU backends (parity/testing path).
    use_pallas: bool = False
    pallas_interpret: bool = False
    # Host-paced compaction (split-group mode, engine/split.py): the
    # tick stops auto-advancing `applied` to `commit`, leaving the host
    # to raise it as its state machine actually applies — so ring
    # compaction can never pass an index whose entry term the host
    # still needs (payload term-arbitration reads it from the ring).
    # Off for the throughput path: device-paced applied keeps the ring
    # compacting without host round-trips.
    host_paced_compaction: bool = False
    # PreVote (etcd/TiKV-style, beyond the reference): an election
    # timeout launches a NON-BINDING prevote round at term+1 first;
    # only a prevote quorum promotes to a real candidacy.  Voters that
    # heard a live leader within ELECT_MIN ticks refuse, so a replica
    # rejoining from a partition cannot depose a healthy leader by
    # term inflation.  Default ON; ``MRT_PREVOTE=0`` restores the
    # reference-faithful legacy elections (the CI A/B's second arm).
    prevote: bool = dataclasses.field(default_factory=prevote_default)
    # Check-quorum (etcd CheckQuorum analog): a leader that has not
    # heard an append reply from a quorum within ELECT_MAX ticks
    # demotes itself to follower AT ITS OWN TERM — a quorum-severed
    # leader releases its groups instead of wedging them while clerk
    # traffic piles into a log that can never commit.  The demotion
    # keeps ``voted_for`` (clearing it would allow a second same-term
    # grant and break election safety).  Default ON;
    # ``MRT_CHECK_QUORUM=0`` is the kill switch.
    check_quorum: bool = dataclasses.field(
        default_factory=check_quorum_default
    )
    # Joint-consensus membership change (Raft §6 / thesis §4.3): per-
    # replica config views as voter BITMASKS (``voters_old`` /
    # ``voters_new``, i32 bit p = peer p votes) plus a ``joint`` flag.
    # While joint, vote tallying, quorum-median commit advance and
    # check-quorum stepdown each require BOTH quorums (two masked
    # reductions).  Config entries take effect ON APPEND (not commit):
    # a replica always reasons with the latest config in its log.
    # Math note: with a full mask (the init state) the masked reduction
    # needs ``P//2+1`` of ``P`` voters and ignores no lanes — exactly
    # the legacy ``cfg.quorum`` single-quorum math, so membership=True
    # is a no-op until the first config entry.  The Pallas tally/commit
    # kernels are mask-unaware, so masked math runs only on the jnp
    # path: ``membership_on`` is gated off under ``use_pallas`` and the
    # host admin ops refuse to start a reconfig there.
    membership: bool = dataclasses.field(
        default_factory=membership_default
    )

    def __post_init__(self) -> None:
        # The ring-log algebra requires headroom: vectorized scatters
        # assume message slots are distinct mod L, and the capacity /
        # compaction thresholds assume an E+INGEST+2 reserve.
        if self.L <= self.E + self.INGEST + 2:
            raise ValueError(
                f"EngineConfig: L={self.L} must exceed "
                f"E+INGEST+2={self.E + self.INGEST + 2}"
            )
        if self.P < 1 or self.G < 1 or self.E < 1:
            raise ValueError("EngineConfig: G, P, E must be >= 1")
        if self.ELECT_MIN >= self.ELECT_MAX or self.HB_TICKS < 1:
            raise ValueError("EngineConfig: bad timing parameters")
        if self.membership and self.P > 30:
            # Voter sets are i32 bitmasks; bit 31 is the sign bit.
            raise ValueError(
                f"EngineConfig: membership mode supports P <= 30 "
                f"(i32 voter bitmasks), got P={self.P}"
            )

    @property
    def quorum(self) -> int:
        return self.P // 2 + 1

    @property
    def membership_on(self) -> bool:
        """Membership machinery active in the tick: requires the jnp
        reduction path (the Pallas kernels are mask-unaware)."""
        return self.membership and not self.use_pallas

    @property
    def full_voters(self) -> int:
        """The all-peers voter bitmask (the init config)."""
        return (1 << self.P) - 1


class EngineState(NamedTuple):
    """Struct-of-arrays Raft state, leading axes (G, P)."""

    tick_no: jnp.ndarray  # i32 scalar
    term: jnp.ndarray  # i32[G,P]
    voted_for: jnp.ndarray  # i32[G,P] (-1 = none)
    role: jnp.ndarray  # i32[G,P]
    commit: jnp.ndarray  # i32[G,P]
    applied: jnp.ndarray  # i32[G,P]
    base: jnp.ndarray  # i32[G,P] snapshot index (log ring floor)
    base_term: jnp.ndarray  # i32[G,P]
    log_len: jnp.ndarray  # i32[G,P] entries above base
    log_term: jnp.ndarray  # i32[G,P,L] ring: abs index i at slot i % L
    next_idx: jnp.ndarray  # i32[G,P,P] leader p's next for peer q
    match_idx: jnp.ndarray  # i32[G,P,P]
    votes: jnp.ndarray  # bool[G,P,P] candidate p's votes from q
    elect_dl: jnp.ndarray  # i32[G,P] election deadline tick
    hb_due: jnp.ndarray  # i32[G,P] next heartbeat tick
    alive: jnp.ndarray  # bool[G,P] fault-injection: replica up
    pre_votes: jnp.ndarray  # bool[G,P,P] prevote grants (prevote mode)
    last_heard: jnp.ndarray  # i32[G,P] last tick a leader was heard
    last_ack: jnp.ndarray  # i32[G,P,P] leader p: last ack tick from q
    # Membership (joint consensus): each replica's VIEW of its group's
    # config — voter bitmasks, the joint flag, a monotone config epoch
    # and the log index of the latest config entry.  Equal old/new
    # masks outside the joint phase (the invariant that makes the
    # dual-quorum reductions branchless).
    voters_old: jnp.ndarray  # i32[G,P] bitmask: C_old voters
    voters_new: jnp.ndarray  # i32[G,P] bitmask: C_new voters
    joint: jnp.ndarray  # bool[G,P] in the C_old,new transition
    cfg_epoch: jnp.ndarray  # i32[G,P] config generation counter
    cfg_idx: jnp.ndarray  # i32[G,P] log index of the latest cfg entry


class Mailbox(NamedTuple):
    """Dense per-edge messages, all ``[G, src, dst]`` (+ trailing dims)."""

    # RequestVote (reference: raft/raft_rpc.go RequestVote args/reply);
    # the ``pre`` bits mark non-binding PreVote rounds.
    vr_active: jnp.ndarray  # bool[G,P,P]
    vr_term: jnp.ndarray  # i32[G,P,P]
    vr_last_idx: jnp.ndarray  # i32[G,P,P]
    vr_last_term: jnp.ndarray  # i32[G,P,P]
    vr_pre: jnp.ndarray  # bool[G,P,P]
    vp_active: jnp.ndarray  # bool[G,P,P]  src=voter, dst=candidate
    vp_term: jnp.ndarray  # i32[G,P,P]
    vp_granted: jnp.ndarray  # bool[G,P,P]
    vp_pre: jnp.ndarray  # bool[G,P,P]
    # AppendEntries / InstallSnapshot (snap flag)
    ar_active: jnp.ndarray  # bool[G,P,P]
    ar_term: jnp.ndarray  # i32[G,P,P]
    ar_prev_idx: jnp.ndarray  # i32[G,P,P]
    ar_prev_term: jnp.ndarray  # i32[G,P,P]
    ar_n: jnp.ndarray  # i32[G,P,P] entries carried (<= E)
    ar_terms: jnp.ndarray  # i32[G,P,P,E]
    ar_commit: jnp.ndarray  # i32[G,P,P] leader commit
    ar_snap: jnp.ndarray  # bool[G,P,P] InstallSnapshot fast-forward
    ap_active: jnp.ndarray  # bool[G,P,P]  src=follower, dst=leader
    ap_term: jnp.ndarray  # i32[G,P,P]
    ap_success: jnp.ndarray  # bool[G,P,P]
    ap_match: jnp.ndarray  # i32[G,P,P]
    ap_conflict: jnp.ndarray  # i32[G,P,P]
    # Leader config view, broadcast with every append: a follower whose
    # log provably covers ``ar_cfg_idx`` mirrors the leader's view
    # (effect-on-append without per-entry payload plumbing — see the
    # phase-3 adoption note in tick_impl).
    ar_cfg_epoch: jnp.ndarray  # i32[G,P,P]
    ar_cfg_idx: jnp.ndarray  # i32[G,P,P]
    ar_cfg_old: jnp.ndarray  # i32[G,P,P] voter bitmask
    ar_cfg_new: jnp.ndarray  # i32[G,P,P] voter bitmask
    ar_cfg_joint: jnp.ndarray  # bool[G,P,P]


def init_state(cfg: EngineConfig, key: jax.Array) -> EngineState:
    G, P, L = cfg.G, cfg.P, cfg.L
    z = lambda *s: jnp.zeros(s, jnp.int32)
    deadlines = jax.random.randint(
        key, (G, P), cfg.ELECT_MIN, cfg.ELECT_MAX, dtype=jnp.int32
    )
    return EngineState(
        tick_no=jnp.int32(0),
        term=z(G, P),
        voted_for=jnp.full((G, P), -1, jnp.int32),
        role=z(G, P),
        commit=z(G, P),
        applied=z(G, P),
        base=z(G, P),
        base_term=z(G, P),
        log_len=z(G, P),
        log_term=z(G, P, L),
        next_idx=jnp.ones((G, P, P), jnp.int32),
        match_idx=z(G, P, P),
        votes=jnp.zeros((G, P, P), bool),
        elect_dl=deadlines,
        hb_due=z(G, P),
        alive=jnp.ones((G, P), bool),
        pre_votes=jnp.zeros((G, P, P), bool),
        last_heard=z(G, P),
        last_ack=z(G, P, P),
        voters_old=jnp.full((G, P), cfg.full_voters, jnp.int32),
        voters_new=jnp.full((G, P), cfg.full_voters, jnp.int32),
        joint=jnp.zeros((G, P), bool),
        cfg_epoch=z(G, P),
        cfg_idx=z(G, P),
    )


def empty_mailbox(cfg: EngineConfig) -> Mailbox:
    G, P, E = cfg.G, cfg.P, cfg.E
    b = lambda *s: jnp.zeros(s, bool)
    z = lambda *s: jnp.zeros(s, jnp.int32)
    return Mailbox(
        vr_active=b(G, P, P), vr_term=z(G, P, P),
        vr_last_idx=z(G, P, P), vr_last_term=z(G, P, P),
        vr_pre=b(G, P, P),
        vp_active=b(G, P, P), vp_term=z(G, P, P), vp_granted=b(G, P, P),
        vp_pre=b(G, P, P),
        ar_active=b(G, P, P), ar_term=z(G, P, P),
        ar_prev_idx=z(G, P, P), ar_prev_term=z(G, P, P),
        ar_n=z(G, P, P), ar_terms=z(G, P, P, E), ar_commit=z(G, P, P),
        ar_snap=b(G, P, P),
        ap_active=b(G, P, P), ap_term=z(G, P, P), ap_success=b(G, P, P),
        ap_match=z(G, P, P), ap_conflict=z(G, P, P),
        ar_cfg_epoch=z(G, P, P), ar_cfg_idx=z(G, P, P),
        ar_cfg_old=z(G, P, P), ar_cfg_new=z(G, P, P),
        ar_cfg_joint=b(G, P, P),
    )


# ---------------------------------------------------------------------------
# Ring-log helpers (the device mirror of raft/raft_log.go's index algebra)
#
# TPU-critical: computed-index gather/scatter on the minor axis are
# catastrophically slow on TPU (measured ~8-17 ms per op at the bench
# shapes vs ~0.05 ms for a fused pass).  Every ring access is therefore
# expressed as compare+select+reduce over the static L axis — XLA fuses
# the on-the-fly one-hot into a single vectorized pass, so the (…,K,L)
# intermediate never reaches HBM.
# ---------------------------------------------------------------------------


def _ring_read(log: jnp.ndarray, idx: jnp.ndarray, L: int) -> jnp.ndarray:
    """Gather ``log[..., idx mod L]`` without a gather op.

    ``log``: [..., L]; ``idx``: [..., K] absolute indices (broadcastable
    prefix). Returns [..., K].  Slots outside the ring window read
    whatever the ring holds — callers mask validity, as with the gather
    formulation.
    """
    slot = jnp.mod(idx, L)  # [..., K]
    lanes = jnp.arange(L, dtype=slot.dtype)
    onehot = slot[..., None] == lanes  # [..., K, L] (fused, never stored)
    return jnp.sum(jnp.where(onehot, log[..., None, :], 0), axis=-1)


def _ring_write(
    log: jnp.ndarray,
    start: jnp.ndarray,
    vals: jnp.ndarray,
    n: jnp.ndarray,
    L: int,
) -> jnp.ndarray:
    """Write ``vals[..., e] → slot (start+e) mod L`` for ``e < n``,
    scatter-free.

    ``log``: [..., L]; ``start``: [...] first absolute index written;
    ``vals``: [..., E]; ``n``: [...] entries to write (≤ E ≤ L, so each
    written slot is hit by at most one message entry).
    """
    E = vals.shape[-1]
    lanes = jnp.arange(L, dtype=start.dtype)
    # Which message entry lands on lane l (unique since E <= L).
    e_l = jnp.mod(lanes - start[..., None], L)  # [..., L]
    hit = e_l < n[..., None]  # [..., L]
    ei = jnp.arange(E, dtype=start.dtype)
    v = jnp.sum(
        jnp.where(e_l[..., None] == ei, vals[..., None, :], 0), axis=-1
    )  # [..., L] (fused)
    return jnp.where(hit, v, log)


def _sort_cols(x: jnp.ndarray) -> list:
    """Ascending sort along the (static, small) last axis via an
    unrolled compare-swap network — ``jnp.sort`` costs ~1.6 ms at bench
    shapes where this is a handful of fused min/max passes.  Returns
    the sorted columns as a list of [...] arrays."""
    cols = [x[..., i] for i in range(x.shape[-1])]
    n = len(cols)
    for i in range(n):
        for j in range(n - 1 - i):
            a, b = cols[j], cols[j + 1]
            cols[j], cols[j + 1] = jnp.minimum(a, b), jnp.maximum(a, b)
    return cols


def _kth_smallest(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """k-th smallest (0-based) along the last axis (see _sort_cols)."""
    return _sort_cols(x)[k]


def _voter_lanes(bits: jnp.ndarray, P: int) -> jnp.ndarray:
    """Expand an i32 voter bitmask [...] to a bool lane mask [..., P]."""
    qi = jnp.arange(P, dtype=jnp.int32)
    return ((bits[..., None] >> qi) & 1) == 1


def _quorum_met(grants: jnp.ndarray, bits: jnp.ndarray, P: int) -> jnp.ndarray:
    """Does ``grants`` (bool[..., P]) contain a majority of the voters
    named by ``bits`` (i32 bitmask [...])?  The masked generalization of
    ``count >= cfg.quorum``: with a full mask it needs P//2+1 of P."""
    lanes = _voter_lanes(bits, P)
    n = jnp.sum((grants & lanes).astype(jnp.int32), axis=-1)
    need = jnp.sum(lanes.astype(jnp.int32), axis=-1) // 2 + 1
    return n >= need


def _quorum_kth(vals: jnp.ndarray, bits: jnp.ndarray, P: int) -> jnp.ndarray:
    """Largest v such that a majority of the voters in ``bits`` have
    ``vals >= v`` — the masked, dynamic-quorum generalization of
    ``_kth_smallest(vals, P - quorum)``.  Non-voter lanes are pushed
    below every real value (sentinel -1), so the top ``count(bits)``
    sorted columns are exactly the voters and the majority-th largest
    overall equals the majority-th largest among voters."""
    lanes = _voter_lanes(bits, P)
    need = jnp.sum(lanes.astype(jnp.int32), axis=-1) // 2 + 1  # [...]
    cols = _sort_cols(jnp.where(lanes, vals, -1))
    k = P - need  # dynamic per-element index into the ascending sort
    out = cols[0]
    for i in range(1, P):
        out = jnp.where(k == i, cols[i], out)
    return out


def _term_at(cfg: EngineConfig, state: EngineState, idx: jnp.ndarray) -> jnp.ndarray:
    """Term of absolute index ``idx`` per replica; idx shape [G,P].
    idx == base → base_term; out-of-window reads return 0 (callers mask)."""
    gathered = _ring_read(state.log_term, idx[..., None], cfg.L)[..., 0]
    return jnp.where(idx == state.base, state.base_term, gathered)


def _last_index(state: EngineState) -> jnp.ndarray:
    return state.base + state.log_len


def _step_down(
    cfg: EngineConfig,
    state: EngineState,
    higher: jnp.ndarray,
    m_term: jnp.ndarray,
    clear_vote: bool = True,
) -> EngineState:
    """Observe a higher term: adopt it, clear the vote, drop to
    follower (reference: the term-check prologue of every RPC handler).
    In prevote mode a term bump also invalidates any prevote round in
    flight — its grants were collected at a now-stale term.

    ``clear_vote=False`` is the check-quorum entry: the demotion
    happens AT THE LEADER'S OWN TERM, where the vote must survive —
    the leader voted for itself at this term, and releasing that vote
    would let a concurrent same-term candidate collect a second grant
    from this replica (two leaders at one term)."""
    kw = dict(
        term=jnp.where(higher, m_term, state.term),
        role=jnp.where(higher, FOLLOWER, state.role),
    )
    if clear_vote:
        kw["voted_for"] = jnp.where(higher, -1, state.voted_for)
    if cfg.prevote:
        kw["pre_votes"] = jnp.where(
            higher[..., None], False, state.pre_votes
        )
    return state._replace(**kw)


# ---------------------------------------------------------------------------
# The tick
# ---------------------------------------------------------------------------


def tick_impl(
    cfg: EngineConfig,
    state: EngineState,
    inbox: Mailbox,
    new_cmds: jnp.ndarray,  # i32[G]: Start() firehose, appended at leaders
    key: jax.Array,
) -> Tuple[EngineState, Mailbox, Dict[str, jnp.ndarray]]:
    G, P, L, E = cfg.G, cfg.P, cfg.L, cfg.E
    out = empty_mailbox(cfg)
    now = state.tick_no + 1
    commit_before = state.commit

    pi = jnp.arange(P)[None, :]  # [1,P] replica index grid

    # One jitter draw per tick, shared by every timer reset in this
    # tick: per-draw PRNG costs ~150 us at bench shapes, and within a
    # single tick the resets are interchangeable — cross-tick
    # desynchronization (what liveness needs) comes from folding the
    # key per tick.
    jitter = jax.random.randint(
        jax.random.fold_in(key, 7), (G, P),
        cfg.ELECT_MIN, cfg.ELECT_MAX, dtype=jnp.int32,
    )

    # ---- 1. vote requests (reference: raft/raft_election.go:54-77) ----
    # All candidates arbitrated in ONE pass (fused r04: the per-src
    # loop emitted P dependent kernel chains; the roofline showed the
    # tick is launch-bound, not bandwidth-bound).  Semantics: the voter
    # first adopts the max incoming term (one step-down covers every
    # request), then grants at most one vote — to ``voted_for`` if
    # already bound, else to the LOWEST-index eligible candidate, which
    # is exactly the old loop's order.  Requests below the adopted term
    # are refused; the old loop could grant them when they arrived
    # "first", but that is just a different message interleaving, and
    # Raft is ordering-robust (the mailbox is at-most-once).  PreVote
    # requests (vr_pre lanes) stay non-binding: no step-down, no
    # voted_for, no timer reset.
    # View [G, voter(dst), cand(src)] — matches out.vp's [G,src,dst].
    vT = lambda x: jnp.swapaxes(x, 1, 2)
    arrived = vT(inbox.vr_active) & state.alive[:, :, None]
    is_pre = vT(inbox.vr_pre)
    active = arrived & ~is_pre
    m_term = vT(inbox.vr_term)
    higher_lane = active & (m_term > state.term[..., None])
    adopt = jnp.max(jnp.where(higher_lane, m_term, -1), axis=2)
    state = _step_down(cfg, state, jnp.any(higher_lane, axis=2), adopt)
    last_idx = _last_index(state)
    last_term = _term_at(cfg, state, last_idx)
    up_to_date = (vT(inbox.vr_last_term) > last_term[..., None]) | (
        (vT(inbox.vr_last_term) == last_term[..., None])
        & (vT(inbox.vr_last_idx) >= last_idx[..., None])
    )
    eligible = active & (m_term == state.term[..., None]) & up_to_date
    cand_ids = jnp.arange(P, dtype=jnp.int32)
    bound = state.voted_for != -1  # [G,P] at voter
    cand_ok = eligible & jnp.where(
        bound[..., None], cand_ids == state.voted_for[..., None], True
    )
    winner = jnp.min(jnp.where(cand_ok, cand_ids, P), axis=2)  # [G,P]
    grant = cand_ok & (cand_ids == winner[..., None])  # ≤1 true per voter
    grant_any = winner < P
    state = state._replace(
        voted_for=jnp.where(grant_any, winner, state.voted_for),
        elect_dl=jnp.where(grant_any, now + jitter, state.elect_dl),
        last_heard=jnp.where(grant_any, now, state.last_heard),
    )
    if cfg.prevote:
        pre_act = arrived & is_pre
        # Grant iff the proposed term would win AND the log is up
        # to date AND this voter has not heard a live leader within
        # ELECT_MIN ticks (the disruption guard).  A LEADER never
        # grants: it is in-lease by definition (its own last_heard
        # is not refreshed while leading — etcd refuses likewise).
        lease_expired = (now - state.last_heard) >= cfg.ELECT_MIN
        grant_pre = (
            pre_act
            & (state.role != LEADER)[..., None]
            & (m_term > state.term[..., None])
            & lease_expired[..., None]
            & up_to_date
        )
    else:
        pre_act = jnp.zeros_like(active)
        grant_pre = pre_act
    # Reply lanes [G, voter, cand] ARE out.vp's [G, src, dst] layout.
    # A src sends either a real or a pre request per tick, so the lanes
    # are disjoint; merge into one write.  A GRANTED pre reply echoes
    # the proposed term (the tally matches on it); a REFUSED pre reply
    # carries the voter's actual term, so a candidate probing a
    # partition-stale term learns the real one and steps down (sim
    # parity: node.py _on_prevote_reply; etcd does the same).
    out = out._replace(
        vp_active=active | pre_act,
        vp_pre=pre_act,
        vp_term=jnp.where(
            pre_act & grant_pre,
            m_term,
            jnp.broadcast_to(state.term[..., None], (G, P, P)),
        ),
        vp_granted=jnp.where(pre_act, grant_pre, grant),
    )

    # ---- 2. vote replies → tally → leadership
    # (reference: raft/raft_election.go:27-49) ----
    # Replies commute: the tally is an OR per voter slot and step-down
    # adopts the max reply term, so the whole phase is one elementwise
    # pass over the [G, cand(dst), voter(src)] view (fused r04; the old
    # per-src loop serialized P dependent chains for an order-invariant
    # reduction).
    arrived = vT(inbox.vp_active) & state.alive[:, :, None]
    reply_pre = vT(inbox.vp_pre)
    active = arrived & ~reply_pre
    m_term = vT(inbox.vp_term)
    granted = vT(inbox.vp_granted)
    higher_lane = active & (m_term > state.term[..., None])
    if cfg.prevote:
        # A refused pre reply carries the voter's actual term (see
        # phase 1): adopt a higher one just like the sim does —
        # without this, a candidate never learns a voter's real
        # term from a prevote refusal (liveness lag).
        higher_lane = higher_lane | (
            arrived & reply_pre & ~granted & (m_term > state.term[..., None])
        )
    adopt = jnp.max(jnp.where(higher_lane, m_term, -1), axis=2)
    state = _step_down(cfg, state, jnp.any(higher_lane, axis=2), adopt)
    good = (
        active
        & (state.role == CANDIDATE)[..., None]
        & (m_term == state.term[..., None])
        & granted
    )
    state = state._replace(votes=state.votes | good)
    if cfg.prevote:
        # Pre replies echo the proposed term (our term+1); stale
        # rounds (term moved on) are discarded.
        good_pre = (
            arrived
            & reply_pre
            & (m_term == state.term[..., None] + 1)
            & granted
        )
        state = state._replace(pre_votes=state.pre_votes | good_pre)

    if cfg.prevote:
        # Prevote quorum → promote to a REAL candidacy (the only place
        # a term bump happens in prevote mode).  The real vote requests
        # go out in phase 5 via ``promote``.
        diag = jnp.arange(P)[None, :, None] == jnp.arange(P)[None, None, :]
        if cfg.membership_on:
            # Joint phase: a prevote round wins only with BOTH quorums
            # (equal masks outside joint make this the single-quorum
            # check).  A candidate tallies against its OWN config view
            # — the latest config in its log, per effect-on-append.
            promote = (
                state.alive
                & (state.role != LEADER)
                & _quorum_met(state.pre_votes, state.voters_old, P)
                & _quorum_met(state.pre_votes, state.voters_new, P)
            )
        else:
            n_pre = jnp.sum(state.pre_votes, axis=-1)  # [G,P]
            promote = (
                state.alive & (state.role != LEADER) & (n_pre >= cfg.quorum)
            )
        state = state._replace(
            term=jnp.where(promote, state.term + 1, state.term),
            role=jnp.where(promote, CANDIDATE, state.role),
            voted_for=jnp.where(promote, pi, state.voted_for),
            votes=jnp.where(promote[..., None], diag, state.votes),
            pre_votes=jnp.where(promote[..., None], False, state.pre_votes),
            elect_dl=jnp.where(promote, now + jitter, state.elect_dl),
        )
    else:
        promote = None
    if cfg.membership_on:
        # Leadership needs a majority of C_old AND (while joint) of
        # C_new — the two masked tallies that make a config change safe
        # against a disjoint-quorum double election (Raft §6).
        become_leader = (
            (state.role == CANDIDATE)
            & state.alive
            & _quorum_met(state.votes, state.voters_old, P)
            & _quorum_met(state.votes, state.voters_new, P)
        )
    elif cfg.use_pallas:
        from .pallas_ops import vote_tally_pallas

        become_leader = vote_tally_pallas(
            state.votes,
            state.role,
            state.alive,
            cfg.quorum,
            interpret=cfg.pallas_interpret,
        )
    else:
        n_votes = jnp.sum(state.votes, axis=-1)  # [G,P]
        become_leader = (
            (state.role == CANDIDATE) & state.alive & (n_votes >= cfg.quorum)
        )
    if cfg.membership_on:
        # A leader elected while a config change is pending appends a
        # NO-OP at its own term (Raft thesis §6.4 / §3.6.2): the joint
        # or exit entry it inherited carries an older term, and the
        # current-term commit guard would otherwise stall the
        # transition forever on an idle group.  Gated on a pending
        # change so steady-state elections stay entry-free.
        noop = (
            become_leader
            & (state.joint | (state.cfg_idx > state.commit))
            & ((L - 2 - E - state.log_len) >= 1)
        )
        noop_idx = _last_index(state) + 1
        lanes_no = jnp.arange(L, dtype=jnp.int32)
        hit_no = (
            jnp.mod(lanes_no - noop_idx[..., None], L) == 0
        ) & noop[..., None]
        state = state._replace(
            log_term=jnp.where(hit_no, state.term[..., None], state.log_term),
            log_len=state.log_len + noop.astype(jnp.int32),
        )
    last_idx = _last_index(state)
    state = state._replace(
        role=jnp.where(become_leader, LEADER, state.role),
        next_idx=jnp.where(
            become_leader[..., None], (last_idx + 1)[..., None], state.next_idx
        ),
        match_idx=jnp.where(
            become_leader[..., None],
            jnp.where(pi[None] == pi[..., None], last_idx[..., None], 0),
            state.match_idx,
        ),
        hb_due=jnp.where(become_leader, now, state.hb_due),  # immediate HB
    )
    if cfg.check_quorum:
        # A fresh leader starts its check-quorum clock NOW: every peer
        # counts as just-heard, so the demotion below cannot fire off
        # acks owed to a previous reign.
        state = state._replace(
            last_ack=jnp.where(
                become_leader[..., None], now, state.last_ack
            )
        )

    # ---- 3. append requests (reference: raft/raft_append_entry.go:108-162) ----
    # One arbitrated pass (fused r04).  Distinct leaders always carry
    # distinct terms (election safety — a replica's appends all carry
    # terms at which IT led), so per destination at most one incoming
    # append is current: pick the max-term message (tie → lowest src,
    # the old loop's order) as the winner and process exactly it; every
    # other active message is answered with a failure reply carrying
    # our post-adoption term, which is what the old loop did for stale
    # messages and is equivalent to an at-most-once drop for the rare
    # lower-term-processed-first interleaving.
    act_in = vT(inbox.ar_active) & state.alive[:, :, None]  # [G,dst,src]
    m_term_all = vT(inbox.ar_term)
    term_key = jnp.where(act_in, m_term_all, -1)
    max_term_in = jnp.max(term_key, axis=2)  # [G,dst]
    is_max = act_in & (term_key == max_term_in[..., None])
    src_ids = jnp.arange(P, dtype=jnp.int32)
    win_src = jnp.min(jnp.where(is_max, src_ids, P), axis=2)  # [G,dst]
    sel = src_ids == win_src[..., None]  # [G,dst,src] one-hot (or none)
    pick = lambda x: jnp.sum(jnp.where(sel, vT(x), 0), axis=2)
    active = win_src < P  # [G,P] a message arrived at dst
    m_term = pick(inbox.ar_term)
    stale = active & (m_term < state.term)
    ok = active & ~stale
    # Accept leadership: step down, reset election timer.
    higher = ok & (m_term > state.term)
    state = state._replace(
        term=jnp.where(higher, m_term, state.term),
        voted_for=jnp.where(higher, -1, state.voted_for),
        role=jnp.where(ok, FOLLOWER, state.role),
    )
    state = state._replace(
        elect_dl=jnp.where(ok, now + jitter, state.elect_dl),
        last_heard=jnp.where(ok, now, state.last_heard),
    )
    if cfg.prevote:
        # Hearing a live leader ABORTS any in-flight prevote round:
        # grants collected during the leader's hiccup must not
        # promote one tick after we acknowledged it (etcd aborts
        # its campaign on MsgApp/MsgHeartbeat the same way).
        state = state._replace(
            pre_votes=jnp.where(ok[..., None], False, state.pre_votes)
        )

    prev = pick(inbox.ar_prev_idx)
    prev_t = pick(inbox.ar_prev_term)
    n_ent = pick(inbox.ar_n)
    snap = jnp.any(sel & vT(inbox.ar_snap), axis=2)

    # InstallSnapshot fast-forward (reference: raft/raft_snapshot.go:15-54).
    do_snap = ok & snap & (prev > state.commit)
    state = state._replace(
        base=jnp.where(do_snap, prev, state.base),
        base_term=jnp.where(do_snap, prev_t, state.base_term),
        log_len=jnp.where(do_snap, 0, state.log_len),
        commit=jnp.where(do_snap, prev, state.commit),
        applied=jnp.where(do_snap, prev, state.applied),
    )
    snap_handled = ok & snap

    # last AFTER any snapshot rebase so non-append rows keep a
    # consistent (base, len) pair.
    last = _last_index(state)
    apn = ok & ~snap
    in_window = (prev >= state.base) & (prev <= last)
    match = apn & in_window & (_term_at(cfg, state, prev) == prev_t)

    # Write entries prev+1..prev+n, truncating only at a genuine
    # conflict (reference: raft/raft_append_entry.go:146-155).
    # Scatter-free ring write (see _ring_write): slots within one
    # message are distinct mod L (E < L), so the lane mapping is
    # exact.
    ei = jnp.arange(E)  # [E]
    idx = prev[..., None] + 1 + ei  # [G,P,E]
    in_msg = match[..., None] & (ei < n_ent[..., None])
    # Winner's entry terms: [G,dst,src,E] selected down to [G,dst,E].
    incoming = jnp.sum(
        jnp.where(
            sel[..., None], jnp.swapaxes(inbox.ar_terms, 1, 2), 0
        ),
        axis=2,
    )
    exists = idx <= last[..., None]
    overlap = in_msg & exists
    # Steady-state skip: appends land strictly past ``last`` (no
    # overlap with existing entries), so the conflict-check ring
    # read has nothing to compare — elide it under a runtime cond.
    conflict_any = jax.lax.cond(
        jnp.any(overlap),
        lambda _: jnp.any(
            overlap
            & (_ring_read(state.log_term, idx, L) != incoming),
            axis=-1,
        ),
        # zeros_like(match), not zeros((G,P)): under shard_map's
        # rep-tracking both branches must vary over the mesh axis.
        lambda _: jnp.zeros_like(match),
        None,
    )  # [G,P]
    log = _ring_write(
        state.log_term, prev + 1, incoming,
        jnp.where(match, n_ent, 0), L,
    )
    state = state._replace(log_term=log)
    msg_last = prev + n_ent
    new_last = jnp.where(
        match,
        jnp.where(conflict_any, msg_last, jnp.maximum(last, msg_last)),
        last,
    )
    state = state._replace(log_len=new_last - state.base)
    # Follower commit (reference: raft/raft_append_entry.go:157-160).
    new_commit = jnp.minimum(pick(inbox.ar_commit), msg_last)
    state = state._replace(
        commit=jnp.where(
            match & (new_commit > state.commit), new_commit, state.commit
        )
    )

    if cfg.membership_on:
        # Config mirroring (effect-on-append without per-entry payload
        # plumbing — a deliberate divergence from entry-parse Raft): a
        # follower adopts the leader's whole config view when a
        # successful append proves its log COVERS the leader's latest
        # config entry (``cfg_idx <= prev + n``: log matching then
        # guarantees the entry at cfg_idx is the leader's).  Truncation
        # rollback falls out for free — a new leader with an older
        # config re-mirrors its view the same way.  A snapshot
        # fast-forward adopts unconditionally: config is part of
        # snapshot state (reference: raft/raft_snapshot.go InstallSnapshot
        # carries the config in etcd/thesis Raft).
        m_cfg_idx = pick(inbox.ar_cfg_idx)
        covered = m_cfg_idx <= (prev + n_ent)
        adopt_cfg = (match & covered) | do_snap
        m_joint = jnp.any(sel & vT(inbox.ar_cfg_joint), axis=2)
        state = state._replace(
            voters_old=jnp.where(
                adopt_cfg, pick(inbox.ar_cfg_old), state.voters_old
            ),
            voters_new=jnp.where(
                adopt_cfg, pick(inbox.ar_cfg_new), state.voters_new
            ),
            joint=jnp.where(adopt_cfg, m_joint, state.joint),
            cfg_epoch=jnp.where(
                adopt_cfg, pick(inbox.ar_cfg_epoch), state.cfg_epoch
            ),
            cfg_idx=jnp.where(adopt_cfg, m_cfg_idx, state.cfg_idx),
        )

    # Replies go to EVERY active sender ([G,dst,src] is out.ap's
    # [G,src,dst] layout: the replier is out's src).  Only the winner
    # can succeed; losers get failure + our current term, and their
    # per-message msg_last / conflict hints are computed elementwise.
    prev_all = vT(inbox.ar_prev_idx)
    msg_last_all = prev_all + vT(inbox.ar_n)
    # Conflict backoff: the committed prefix always matches, so
    # reposition to min(prev, commit+1) in one round (divergence
    # from the reference's term scan — see module docstring).
    conflict_all = jnp.minimum(prev_all, state.commit[..., None] + 1)
    success = match | snap_handled  # [G,P] winner outcome
    reply_match_w = jnp.where(snap_handled, prev, msg_last)
    out = out._replace(
        ap_active=act_in,
        ap_term=jnp.broadcast_to(state.term[..., None], (G, P, P)),
        ap_success=sel & success[..., None],
        ap_match=jnp.where(sel, reply_match_w[..., None], msg_last_all),
        ap_conflict=conflict_all,
    )

    # ---- 4. append replies + quorum commit advance
    # (reference: raft/raft_append_entry.go:66-105 — the north-star) ----
    # Replies commute: each src's reply touches only its own
    # match/next slot and step-down adopts the max reply term, so the
    # whole phase is one elementwise pass over the
    # [G, leader(dst), src] view (fused r04).
    active = vT(inbox.ap_active) & state.alive[:, :, None]
    m_term = vT(inbox.ap_term)
    higher_lane = active & (m_term > state.term[..., None])
    adopt = jnp.max(jnp.where(higher_lane, m_term, -1), axis=2)
    state = _step_down(cfg, state, jnp.any(higher_lane, axis=2), adopt)
    good = (
        active
        & (state.role == LEADER)[..., None]
        & (m_term == state.term[..., None])
    )
    if cfg.check_quorum:
        # Any current-term reply — success OR conflict — proves the
        # peer is reachable and acknowledges this leadership; both
        # refresh the leader's per-peer last-ack clock.
        state = state._replace(
            last_ack=jnp.where(good, now, state.last_ack)
        )
    succ = good & vT(inbox.ap_success)
    fail = good & ~vT(inbox.ap_success)
    new_match = jnp.maximum(state.match_idx, vT(inbox.ap_match))
    state = state._replace(
        match_idx=jnp.where(succ, new_match, state.match_idx),
        next_idx=jnp.where(
            succ,
            # max(): appends are pipelined (next_idx advances
            # optimistically at send, phase 5c), so an ack for
            # batch k must not rewind past batches k+1... already
            # in flight.
            jnp.maximum(state.next_idx, new_match + 1),
            jnp.where(
                fail,
                # Floor at match_idx+1: a reordered stale
                # failure must not rewind below what this
                # follower has already acked.
                jnp.maximum(
                    jnp.clip(vT(inbox.ap_conflict), 1, None),
                    state.match_idx + 1,
                ),
                state.next_idx,
            ),
        ),
    )

    last_idx = _last_index(state)
    is_leader = (state.role == LEADER) & state.alive
    # Self always matches its own last entry.
    own = pi[None] == pi[..., None]  # [1,P,P] diag mask
    eff_match = jnp.where(own, last_idx[..., None], state.match_idx)
    if cfg.membership_on:
        # Joint commit rule: an index is committed only when a majority
        # of C_old AND a majority of C_new have matched it — the min of
        # the two masked quorum medians (equal outside joint).  A
        # leader REMOVED by the in-flight config still advances commit
        # here: the medians run over the voters' match columns, not the
        # leader's own lane, so it can commit the very entry that
        # removes it (Raft thesis §4.2.2).
        q_old = _quorum_kth(eff_match, state.voters_old, P)
        q_new = _quorum_kth(eff_match, state.voters_new, P)
        quorum_idx = jnp.minimum(q_old, q_new)
        # Current-term guard (reference: raft/raft_append_entry.go:98).
        guard = _term_at(cfg, state, quorum_idx) == state.term
        new_commit = jnp.where(
            is_leader & guard,
            jnp.maximum(state.commit, quorum_idx),
            state.commit,
        )
    elif cfg.use_pallas:
        from .pallas_ops import quorum_commit_pallas

        new_commit = quorum_commit_pallas(
            eff_match,
            state.term,
            state.commit,
            state.base,
            state.base_term,
            state.log_term,
            is_leader,
            cfg.quorum,
            interpret=cfg.pallas_interpret,
        )
    else:
        # k-th smallest via fused compare-swap network (jnp.sort on the
        # P axis costs ~1.6 ms at bench shapes).
        quorum_idx = _kth_smallest(eff_match, P - cfg.quorum)  # the median
        # Current-term guard (reference: raft/raft_append_entry.go:98).
        guard = _term_at(cfg, state, quorum_idx) == state.term
        new_commit = jnp.where(
            is_leader & guard,
            jnp.maximum(state.commit, quorum_idx),
            state.commit,
        )
    state = state._replace(commit=new_commit)

    # ---- 4b. check-quorum: quorum-severed leaders release their
    # groups (etcd CheckQuorum analog; beyond the reference) ----
    if cfg.check_quorum:
        # Quorum-heard tick: the (P-quorum)-th smallest effective ack
        # (self slot = now) has ``quorum`` elements at or above it, so
        # it is the newest tick at which a full quorum had acked.
        eff_ack = jnp.where(own, now, state.last_ack)  # [G,P,P]
        if cfg.membership_on:
            # Joint check-quorum: the leader must be hearing BOTH
            # quorums — losing either one means it can no longer
            # commit, so it releases the group.  Learner acks are
            # masked out: a caught-up learner must never keep a
            # voter-severed leader alive.
            q_heard = jnp.minimum(
                _quorum_kth(eff_ack, state.voters_old, P),
                _quorum_kth(eff_ack, state.voters_new, P),
            )
        else:
            q_heard = _kth_smallest(eff_ack, P - cfg.quorum)  # [G,P]
        demote = (
            (state.role == LEADER)
            & state.alive
            & ((now - q_heard) >= cfg.ELECT_MAX)
        )
        state = _step_down(
            cfg, state, demote, state.term, clear_vote=False
        )
        # Full randomized backoff before the deposed leader campaigns:
        # while severed its prevotes cannot win anyway, and on heal the
        # surviving side's leader should not be raced immediately.
        state = state._replace(
            elect_dl=jnp.where(demote, now + jitter, state.elect_dl)
        )

    # ---- 4c. membership: a leader removed by a COMPLETED config
    # change steps down once the removing entry commits (Raft thesis
    # §4.2.2: it keeps leading — and committing — up to that point) ----
    if cfg.membership_on:
        self_voter = (
            ((state.voters_old | state.voters_new) >> pi) & 1
        ) == 1  # [G,P]
        removed = (
            (state.role == LEADER)
            & state.alive
            & ~state.joint
            & ~self_voter
            & (state.commit >= state.cfg_idx)
        )
        # Own-term demotion, like check-quorum: no higher term was
        # observed, so the vote must survive.
        state = _step_down(cfg, state, removed, state.term, clear_vote=False)
        state = state._replace(
            elect_dl=jnp.where(removed, now + jitter, state.elect_dl)
        )

    # ---- 5. timers: elections (reference: raft/raft.go:106-125) ----
    timeout = state.alive & (now >= state.elect_dl) & (state.role != LEADER)
    if cfg.membership_on:
        # Non-voters (learners, removed peers) never campaign: their
        # own config view excludes them from both voter sets.  They
        # still GRANT votes — eligibility is the candidate's config,
        # tallied under the candidate's masks above.
        member = (((state.voters_old | state.voters_new) >> pi) & 1) == 1
        timeout = timeout & member
    if not cfg.prevote:
        state = state._replace(
            term=jnp.where(timeout, state.term + 1, state.term),
            role=jnp.where(timeout, CANDIDATE, state.role),
            voted_for=jnp.where(timeout, pi, state.voted_for),
            votes=jnp.where(timeout[..., None], own[0][None], state.votes),
            elect_dl=jnp.where(timeout, now + jitter, state.elect_dl),
        )
        send_real = timeout
        send_pre = jnp.zeros_like(timeout)
    else:
        # Timeout launches a fresh NON-BINDING prevote round: grant
        # ourselves, ask peers at term+1, reset the retry window.  No
        # term bump, no role change — promotion happened in phase 2.
        state = state._replace(
            pre_votes=jnp.where(timeout[..., None], own[0][None],
                                state.pre_votes),
            elect_dl=jnp.where(timeout, now + jitter, state.elect_dl),
        )
        # Phase-2 promotions announce immediately — unless a later
        # phase (3/4) already deposed the fresh candidate on a
        # higher-term message: a FOLLOWER must not broadcast real
        # RequestVote (voters would burn voted_for for a node that can
        # never tally them).
        send_real = promote & (state.role == CANDIDATE)
        send_pre = timeout  # disjoint: promote reset elect_dl this tick
    last_idx = _last_index(state)
    last_term = _term_at(cfg, state, last_idx)
    # Vote requests to every peer (dst masked to alive senders; self slot
    # excluded).
    sending = send_real | send_pre
    vr_act = sending[:, :, None] & ~own & state.alive[:, :, None]
    vr_term_per = jnp.where(send_pre, state.term + 1, state.term)
    out = out._replace(
        vr_active=vr_act,
        vr_term=jnp.broadcast_to(vr_term_per[:, :, None], (G, P, P)),
        vr_last_idx=jnp.broadcast_to(last_idx[:, :, None], (G, P, P)),
        vr_last_term=jnp.broadcast_to(last_term[:, :, None], (G, P, P)),
        vr_pre=jnp.broadcast_to(send_pre[:, :, None], (G, P, P)) & vr_act,
    )

    # ---- 5a-bis. membership: joint auto-exit.  A leader whose
    # C_old,new entry has COMMITTED appends the C_new exit entry
    # in-tick (no host round-trip in the transition's critical path)
    # and adopts it immediately — effect-on-append collapses old to
    # new, ending the dual-quorum phase.  Placed before ingest so the
    # capacity accounting and ``last_idx`` the firehose sees already
    # include the exit entry. ----
    if cfg.membership_on:
        last_idx = _last_index(state)
        can_exit = (
            (state.role == LEADER)
            & state.alive
            & state.joint
            & (state.commit >= state.cfg_idx)
            & ((L - 2 - E - state.log_len) >= 1)
        )
        exit_idx = last_idx + 1
        lanes_cfg = jnp.arange(L, dtype=jnp.int32)
        hit_cfg = (
            jnp.mod(lanes_cfg - exit_idx[..., None], L) == 0
        ) & can_exit[..., None]
        state = state._replace(
            log_term=jnp.where(
                hit_cfg, state.term[..., None], state.log_term
            ),
            log_len=state.log_len + can_exit.astype(jnp.int32),
            voters_old=jnp.where(
                can_exit, state.voters_new, state.voters_old
            ),
            joint=jnp.where(can_exit, False, state.joint),
            cfg_epoch=jnp.where(
                can_exit, state.cfg_epoch + 1, state.cfg_epoch
            ),
            cfg_idx=jnp.where(can_exit, exit_idx, state.cfg_idx),
        )

    # ---- 5b. Start() ingestion: leaders append the firehose ----
    # Only the leader at the group's max alive term ingests: a zombie
    # leader (older term, still alive under message loss) can never
    # commit what it accepts, and letting it accept would corrupt the
    # per-group accepted/start_index payload-binding metrics (there is
    # exactly one leader per term by election safety).
    is_leader = (state.role == LEADER) & state.alive  # [G,P]
    group_max_term = jnp.max(
        jnp.where(state.alive, state.term, -1), axis=1, keepdims=True
    )
    is_leader = is_leader & (state.term == group_max_term)
    capacity = jnp.maximum(L - 2 - cfg.E - state.log_len, 0)
    want = jnp.minimum(new_cmds[:, None], cfg.INGEST)  # [G,P]
    accept = jnp.where(is_leader, jnp.minimum(want, capacity), 0)
    last_idx = _last_index(state)
    # Scatter-free lane write: every ingested entry carries the leader's
    # current term, so the per-lane value is just ``term`` — no inner
    # entry gather needed at all.
    lanes = jnp.arange(L, dtype=jnp.int32)
    e_l = jnp.mod(lanes - (last_idx[..., None] + 1), L)  # [G,P,L]
    hit = e_l < accept[..., None]
    log = jnp.where(hit, state.term[..., None], state.log_term)
    state = state._replace(log_term=log, log_len=state.log_len + accept)
    # Group accepted count (for host payload binding): the max-term
    # gate above guarantees at most one accepting replica per group,
    # so sum exactly collapses the P axis.
    accepted_per_group = jnp.sum(accept, axis=1)  # i32[G]
    start_index = jnp.sum(jnp.where(accept > 0, last_idx, 0), axis=1)

    # ---- 5c. append sends: heartbeat + lag repair
    # (reference: raft/raft_append_entry.go:4-65; heartbeats are full
    # appends carrying missing suffix) ----
    last_idx = _last_index(state)
    is_leader = (state.role == LEADER) & state.alive
    hb_fire = is_leader & (now >= state.hb_due)
    lag = state.next_idx <= last_idx[:, :, None]  # [G,P,P] dst lags
    send = (hb_fire[:, :, None] | (is_leader[:, :, None] & lag)) & ~own
    send = send & state.alive[:, :, None]
    prev = state.next_idx - 1  # [G,P,P] per (leader, dst)
    need_snap = prev < state.base[:, :, None]
    prev = jnp.where(need_snap, state.base[:, :, None], prev)
    # prev term per (g, p, dst): scatter-free read from sender's ring.
    prev_term = _ring_read(state.log_term, prev, L)  # [G,P,P]
    prev_term = jnp.where(
        prev == state.base[:, :, None], state.base_term[:, :, None], prev_term
    )
    n_send = jnp.where(
        need_snap, 0, jnp.clip(last_idx[:, :, None] - prev, 0, E)
    )
    # Outgoing suffix terms.  Fast path: log terms are monotone
    # non-decreasing and bounded by the sender's own term, so when
    # ``term_at(prev+1) == term`` the ENTIRE suffix carries the current
    # term — the [G,P,P,E]xL bulk ring read (the dominant op of the
    # steady-state tick) collapses to a broadcast.  The check itself is
    # an E-times-cheaper [G,P,P]xL read, and lagging/faulted cases fall
    # back to the exact full read under a runtime cond.
    first_term = _ring_read(state.log_term, prev + 1, L)  # [G,P,P]
    uniform = ~send | (n_send == 0) | (first_term == state.term[:, :, None])

    def _suffix_full(_):
        send_idx = prev[..., None] + 1 + jnp.arange(E)  # [G,P,P,E]
        return _ring_read(
            state.log_term, send_idx.reshape(G, P, P * E), L
        ).reshape(G, P, P, E)

    def _suffix_uniform(_):
        return jnp.broadcast_to(state.term[:, :, None, None], (G, P, P, E))

    t = jax.lax.cond(jnp.all(uniform), _suffix_uniform, _suffix_full, None)
    ar_terms = jnp.where(jnp.arange(E) < n_send[..., None], t, 0)
    out = out._replace(
        ar_active=send,
        ar_term=jnp.broadcast_to(state.term[:, :, None], (G, P, P)),
        ar_prev_idx=prev,
        ar_prev_term=prev_term,
        ar_n=n_send,
        ar_terms=ar_terms,
        ar_commit=jnp.broadcast_to(state.commit[:, :, None], (G, P, P)),
        ar_snap=need_snap & send,
        # Leader config view rides every append (phase-3 mirroring).
        ar_cfg_epoch=jnp.broadcast_to(
            state.cfg_epoch[:, :, None], (G, P, P)
        ),
        ar_cfg_idx=jnp.broadcast_to(state.cfg_idx[:, :, None], (G, P, P)),
        ar_cfg_old=jnp.broadcast_to(
            state.voters_old[:, :, None], (G, P, P)
        ),
        ar_cfg_new=jnp.broadcast_to(
            state.voters_new[:, :, None], (G, P, P)
        ),
        ar_cfg_joint=jnp.broadcast_to(state.joint[:, :, None], (G, P, P)),
    )
    state = state._replace(
        hb_due=jnp.where(hb_fire, now + cfg.HB_TICKS, state.hb_due),
        # Pipelined replication: advance next_idx at send time instead
        # of waiting the 2-tick ack RTT, so a fresh E-batch streams
        # every tick (2x steady-state throughput).  A dropped batch
        # self-heals: the follower's failure reply repositions next_idx
        # via the conflict backoff above.  (The reference replicator is
        # one-at-a-time per peer, raft/raft_append_entry.go:20-65 — a
        # deliberate divergence.)
        next_idx=jnp.where(send, state.next_idx + n_send, state.next_idx),
    )

    # ---- 6. apply frontier + ring compaction ----
    if not cfg.host_paced_compaction:
        state = state._replace(
            applied=jnp.maximum(state.applied, state.commit)
        )
    # Compact when headroom shrinks: advance base over the applied
    # prefix (device analog of service-driven Snapshot(),
    # reference: raft/raft_snapshot.go:3-13).
    headroom = L - state.log_len
    need = headroom < (cfg.E + cfg.INGEST + 2)
    target = jnp.minimum(state.applied, _last_index(state))
    new_base = jnp.where(need, jnp.maximum(state.base, target), state.base)
    new_base_term = _term_at(cfg, state, new_base)
    state = state._replace(
        log_len=_last_index(state) - new_base,
        base=new_base,
        base_term=new_base_term,
    )

    state = state._replace(tick_no=now)

    leader_commit_delta = jnp.where(
        (state.role == LEADER) & state.alive,
        state.commit - commit_before,
        0,
    )
    metrics = {
        "commits": jnp.sum(jnp.maximum(leader_commit_delta, 0)),
        "leaders": jnp.sum((state.role == LEADER) & state.alive),
        "max_term": jnp.max(state.term),
        "accepted": accepted_per_group,
        "start_index": start_index,
        # Term the accepted entries carry (the acceptor is the unique
        # max-term alive leader, so the sum collapses the P axis) —
        # lets the host bind payloads to (index, term), which is
        # unambiguous where index alone is not (conformance rig).
        "accept_term": jnp.sum(jnp.where(accept > 0, state.term, 0), axis=1),
        "commit_index": jnp.max(state.commit, axis=1),  # i32[G]
    }
    assert set(metrics) == set(METRIC_KEYS), (
        "tick metrics drifted from METRIC_KEYS — update core.py's "
        "constants (mesh.py and host.py derive their specs from them)"
    )
    return state, out, metrics


tick = functools.partial(jax.jit, static_argnums=0, donate_argnums=(1,))(
    tick_impl
)

# Per-tick record fields the traced bench loop stacks (i32[n_ticks, G]
# each): the ingest/commit frontiers and accept terms from which the
# bench reconstructs per-entry commit latency (measured, not modeled)
# and per-sampled-group operation histories for porcupine.
TRACE_KEYS = ("ing_hi", "accepted", "accept_term", "commit")


def make_traced_body(cfg: EngineConfig, new_cmds: jnp.ndarray, key: jax.Array):
    """The traced scan body shared by :func:`run_ticks_traced` and the
    mesh variant (engine/mesh.py) — one place derives the TRACE_KEYS
    record from the tick metrics, so the two bench paths can never
    desynchronize."""

    def body(carry, i):
        st, mb = carry
        st, mb, m = tick_impl(cfg, st, mb, new_cmds, jax.random.fold_in(key, i))
        rec = {
            # Last index after this tick's ingest at the accepting
            # leader; 0 on no-accept ticks (host takes a running max).
            "ing_hi": m["start_index"] + m["accepted"],
            "accepted": m["accepted"],
            "accept_term": m["accept_term"],
            "commit": m["commit_index"],
        }
        return (st, mb), rec

    return body


@functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=(1, 2))
def run_ticks(
    cfg: EngineConfig,
    state: EngineState,
    inbox: Mailbox,
    n_ticks: int,
    ingest_per_tick: int,
    key: jax.Array,
) -> Tuple[EngineState, Mailbox]:
    """Device-resident multi-tick loop for the bench path: ``n_ticks``
    consensus rounds under one ``lax.scan`` with a constant Start()
    firehose — zero host round-trips between ticks (the whole point of
    the batched design: SURVEY §7.1's global synchronous tick loop).

    Committed-entry totals are exact from state alone:
    ``sum_g max_p commit[g,p]`` before vs after."""
    new_cmds = jnp.full((cfg.G,), ingest_per_tick, jnp.int32)

    def body(carry, i):
        st, mb = carry
        k = jax.random.fold_in(key, i)
        st, mb, _ = tick_impl(cfg, st, mb, new_cmds, k)
        return (st, mb), None

    (state, inbox), _ = jax.lax.scan(
        body, (state, inbox), jnp.arange(n_ticks, dtype=jnp.int32)
    )
    return state, inbox


@functools.partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=(1, 2))
def run_ticks_traced(
    cfg: EngineConfig,
    state: EngineState,
    inbox: Mailbox,
    n_ticks: int,
    ingest_per_tick: int,
    key: jax.Array,
) -> Tuple[EngineState, Mailbox, Dict[str, jnp.ndarray]]:
    """:func:`run_ticks` plus a per-tick record of the per-group
    ingest/commit frontiers and accept terms (``TRACE_KEYS``, each
    i32[n_ticks, G]) — the raw material for the bench's MEASURED
    commit-latency distribution and its porcupine verification of
    sampled groups (reconstructing each sampled group's operation
    history from what the device actually did, kvraft-style post-hoc
    checking of the flagship run; reference: kvraft test harness
    porcupine pass over the real op history).

    Still device-resident and scan-fused: the records are four [G]
    vectors appended to HBM per tick — noise against the tick's own
    traffic (the bench gates on <=2% throughput cost vs the untraced
    loop)."""
    new_cmds = jnp.full((cfg.G,), ingest_per_tick, jnp.int32)
    body = make_traced_body(cfg, new_cmds, key)
    (state, inbox), rec = jax.lax.scan(
        body, (state, inbox), jnp.arange(n_ticks, dtype=jnp.int32)
    )
    return state, inbox, rec


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1, 2))
def run_ticks_traced_vec(
    cfg: EngineConfig,
    state: EngineState,
    inbox: Mailbox,
    n_ticks: int,
    new_cmds: jnp.ndarray,
    key: jax.Array,
) -> Tuple[EngineState, Mailbox, Dict[str, jnp.ndarray]]:
    """:func:`run_ticks_traced` with a per-group ingest VECTOR — the
    skewed-firehose form (10% hot groups at full rate, the rest
    trickling) the config-#5 capture drives (BASELINE.json configs[4]:
    churn + snapshot storm + skewed shard load at 100k x 5)."""
    body = make_traced_body(cfg, new_cmds, key)
    (state, inbox), rec = jax.lax.scan(
        body, (state, inbox), jnp.arange(n_ticks, dtype=jnp.int32)
    )
    return state, inbox, rec
