"""Load-curve aggregation: windowed fleet scrapes + the knee finder.

The open-loop generator (benchmarks/openloop.py) offers load the
servers cannot refuse; this module turns what the fleet recorded into
the latency-under-load curve the paper's serving story needs:

* :func:`scrape_hists` hits every process's ``Obs.hist`` verb — the
  CUMULATIVE per-stage histogram dumps plus the live queue gauges —
  through the same :class:`~multiraft_tpu.harness.observe.FleetObserver`
  (clock-aligned, control-exempt) the nemesis timeline uses.
* :func:`window_hists` diffs two scrapes (``Hist.sub``) and merges the
  per-process windows into ONE fleet-wide histogram per stage, so each
  rate step reports the p50/p99 of exactly the requests it offered.
  Cumulative-dump-then-diff beats a server-side reset verb: scrapes
  stay read-only (two observers can't clobber each other) and a missed
  scrape degrades to a wider window instead of lost data.
* :func:`find_knee` locates the knee of the throughput-vs-latency
  curve (max distance from the endpoint chord — the Kneedle shape,
  pure and dependency-free), and :func:`max_sustainable` reports the
  highest offered rate whose client p99 stayed under a target.

:func:`run_sweep` ties it together: scrape, fire one open-loop step
(caller-supplied — this module never imports the generator, keeping
harness → benchmarks dependency-free), scrape again, attach the
windowed stage decomposition to the step record.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from ..distributed.profile import SERVING_THREAD_PREFIXES, top_functions
from ..distributed.tail import dominant_wait, merge_drains
from ..utils.metrics import Hist
from .observe import FleetObserver

__all__ = [
    "scrape_hists",
    "window_hists",
    "stage_stats",
    "cpu_stage_stats",
    "gauge_peaks",
    "window_proc_cpu_s",
    "profile_window",
    "tail_window",
    "find_knee",
    "max_sustainable",
    "run_sweep",
    "build_loadcurve",
]


# -- scraping ---------------------------------------------------------------

def scrape_hists(obs: FleetObserver) -> Dict[str, Dict[str, Any]]:
    """One fleet-wide ``Obs.hist`` scrape: ``{"host:port": {"hists":
    {name: Hist}, "gauges": {...}, "now_us": float}}``.  Unreachable
    processes get an explicit ``{"missing": True}`` marker (same
    discipline as ``snapshot_all`` — a silently shorter fleet would
    present a partial window as the whole one)."""
    out: Dict[str, Dict[str, Any]] = {}
    for a in obs.addrs:
        key = f"{a[0]}:{a[1]}"
        dump = obs.hist(a)
        if dump is None:
            out[key] = {"missing": True}
            continue
        out[key] = {
            "hists": {
                name: Hist.from_dump(d)
                for name, d in (dump.get("hists") or {}).items()
            },
            "gauges": dict(dump.get("gauges") or {}),
            "now_us": float(dump.get("now_us", 0.0)),
        }
    return out


def window_hists(
    before: Dict[str, Dict[str, Any]],
    after: Dict[str, Dict[str, Any]],
) -> Dict[str, Hist]:
    """Fleet-wide windowed histograms: per process ``after − before``
    (``Hist.sub``; a process absent from ``before`` — restarted, or
    first scrape — contributes its cumulative hist), then merged
    across processes per metric name.  Exact for counts; window
    extremes are cumulative (Hist.sub's documented approximation)."""
    merged: Dict[str, Hist] = {}
    for key, snap in after.items():
        if snap.get("missing"):
            continue
        prev = before.get(key) or {}
        prev_hists = prev.get("hists") or {}
        for name, h in snap["hists"].items():
            ph = prev_hists.get(name)
            win = Hist.sub(h, ph) if ph is not None else h
            if win.count <= 0:
                continue
            tgt = merged.get(name)
            if tgt is None:
                tgt = merged[name] = Hist()
            tgt.merge(win)
    return merged


def stage_stats(windows: Dict[str, Hist]) -> Dict[str, Dict[str, Any]]:
    """Per-stage decomposition of one window: ``{"wire": {"count",
    "p50_ms", "p99_ms", "mean_ms"}, ...}`` for every ``stage.*_s``
    histogram that saw samples (names shortened to the bare stage)."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, h in sorted(windows.items()):
        if not (name.startswith("stage.") and name.endswith("_s")):
            continue
        stage = name[len("stage."):-len("_s")]
        p50 = h.percentile(0.50)
        p99 = h.percentile(0.99)
        out[stage] = {
            "count": h.count,
            "p50_ms": round(1e3 * p50, 3) if p50 is not None else None,
            "p99_ms": round(1e3 * p99, 3) if p99 is not None else None,
            "mean_ms": round(1e3 * h.total / h.count, 3) if h.count else None,
        }
    return out


def cpu_stage_stats(windows: Dict[str, Hist]) -> Dict[str, Dict[str, Any]]:
    """Per-stage CPU cost accounting for one window: the ``cpu.*_s``
    twins of the wall stages (observe.py's segment-accounting
    vocabulary).  ``cpu_s`` is the window's fleet-wide CPU-seconds sum
    for the stage (Hist.total diffs exactly, like counts), ``count``
    the number of segments — together they answer "which stage burned
    the loop's CPU this step"."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, h in sorted(windows.items()):
        if not (name.startswith("cpu.") and name.endswith("_s")):
            continue
        stage = name[len("cpu."):-len("_s")]
        out[stage] = {
            "count": h.count,
            "cpu_s": round(h.total, 6),
        }
    return out


def window_proc_cpu_s(
    before: Dict[str, Dict[str, Any]],
    after: Dict[str, Dict[str, Any]],
) -> Optional[float]:
    """Fleet-wide process CPU-seconds burned between two scrapes —
    ``gauge.cpu_s`` (the cumulative process CPU clock) diffed per
    process and summed.  Against the step's wall time this says
    whether the fleet was CPU-pegged; None when no process reported
    the gauge on both sides."""
    total, seen = 0.0, False
    for key, snap in after.items():
        if snap.get("missing"):
            continue
        a = (snap.get("gauges") or {}).get("gauge.cpu_s")
        b = ((before.get(key) or {}).get("gauges") or {}).get("gauge.cpu_s")
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            total += max(0.0, float(a) - float(b))
            seen = True
    return round(total, 6) if seen else None


def profile_window(
    obs: FleetObserver, topn: int = 15
) -> Dict[str, Any]:
    """Drain the fleet's sampling profilers (``Obs.profile``) and fold
    the window into its attribution summary: total samples, per-thread
    totals, the top-N functions by self samples — plus the raw merged
    ``flame`` (folded stacks, process-name-prefixed) for callers that
    accumulate a whole-sweep flamegraph.  Drain-on-read gives the same
    windowing the histogram scrapes get from cumulative-diff: each
    call returns exactly the samples since the previous one."""
    dumps = obs.profile_all()
    flame = FleetObserver.fleet_flame(dumps)
    # Fleet-flame keys are "proc;thread;frames..." — attribution rows
    # are the proc;thread pair (per_thread_totals alone would stop at
    # the process segment).
    threads: Dict[str, int] = {}
    unprefixed: Dict[str, int] = {}
    serving: Dict[str, int] = {}
    for k, v in flame.items():
        row = ";".join(k.split(";", 2)[:2])
        threads[row] = threads.get(row, 0) + int(v)
        bare = k.split(";", 1)[1] if ";" in k else k
        unprefixed[bare] = unprefixed.get(bare, 0) + int(v)
        # The sampler records every thread every tick — a main thread
        # parked in sleep shows the same sample rate as a pegged loop.
        # The serving-thread cut ranks only the serving-side threads
        # (SERVING_THREAD_PREFIXES: the per-node loops plus their
        # engine-pump device-wait threads), so the headline names what
        # serving CPU was spent on rather than where idle threads
        # happened to be parked.
        if bare.startswith(SERVING_THREAD_PREFIXES):
            serving[bare] = serving.get(bare, 0) + int(v)
    return {
        "samples": sum(flame.values()),
        "per_thread": threads,
        "top": top_functions(serving or unprefixed, topn),
        "top_all_threads": top_functions(unprefixed, topn),
        "flame": flame,
    }


def tail_window(
    obs: FleetObserver,
    p99_ms: Optional[float] = None,
    keep: int = 8,
) -> Optional[Dict[str, Any]]:
    """Drain the fleet's tail-exemplar stores (``Obs.tail``) and fold
    the window into the step's tail digest: retention counters, the
    ``keep`` slowest exemplars verbatim (full stage + wait vectors —
    the waterfall rows), and the dominant-wait attribution of the tail
    slice.  The slice is every retained exemplar at/above the step's
    client p99 when one is given (those ARE the p99+ requests), else
    the ``keep`` slowest — so ``dominant`` answers "what did the p99
    wait on this step".  ``None`` when no process runs the tail plane
    (MRT_TAIL=0): absent, not zeros, so readers can tell "off" from
    "quiet"."""
    drains = [
        (d or {}).get("tail") for d in obs.tail_all().values()
    ]
    if not any(isinstance(d, dict) for d in drains):
        return None
    merged = merge_drains(drains)
    # slo + topk are both sorted slowest-first; the merged tail keeps
    # the guaranteed outliers ahead of the windowed top-k.
    retained = merged["slo"] + merged["topk"]
    retained.sort(key=lambda e: -(e.get("total_s") or 0.0))
    if p99_ms is not None:
        cut = p99_ms / 1e3
        tail_slice = [e for e in retained if (e.get("total_s") or 0.0) >= cut]
    else:
        tail_slice = []
    if not tail_slice:
        tail_slice = retained[:keep]
    waits: Dict[str, int] = {}
    for e in tail_slice:
        w = dominant_wait(e)
        waits[w] = waits.get(w, 0) + 1
    return {
        "seen": merged["seen"],
        "over_slo": merged["over_slo"],
        "dropped_slo": merged["dropped_slo"],
        "exemplars": retained[:keep],
        "dominant_waits": waits,
        "dominant": (
            max(waits.items(), key=lambda kv: kv[1])[0] if waits else None
        ),
    }


def gauge_peaks(after: Dict[str, Dict[str, Any]]) -> Dict[str, float]:
    """Max of each queue gauge across the fleet at scrape time — the
    step's congestion witness next to its latency decomposition."""
    peaks: Dict[str, float] = {}
    for snap in after.values():
        for name, val in (snap.get("gauges") or {}).items():
            if isinstance(val, (int, float)):
                peaks[name] = max(peaks.get(name, 0.0), float(val))
    return peaks


# -- knee detection ---------------------------------------------------------

def find_knee(
    xs: Sequence[float], ys: Sequence[float],
) -> Optional[int]:
    """Index of the knee of an increasing curve: the point with max
    perpendicular-ish (vertical, after normalization) distance from the
    chord joining the endpoints — the Kneedle construction without the
    smoothing (rate ladders are short and already monotone in x).
    Works for both convex (latency hockey stick: knee is below the
    chord) and concave (throughput rollover: above) shapes by taking
    the absolute distance.  ``None`` when fewer than 3 points or the
    curve is flat."""
    n = len(xs)
    if n != len(ys) or n < 3:
        return None
    x0, x1 = float(xs[0]), float(xs[-1])
    y0, y1 = float(ys[0]), float(ys[-1])
    dx, dy = x1 - x0, y1 - y0
    if dx == 0 or dy == 0:
        return None
    best_i, best_d = None, 0.0
    for i in range(1, n - 1):
        xn = (float(xs[i]) - x0) / dx
        yn = (float(ys[i]) - y0) / dy
        d = abs(yn - xn)  # chord of the normalized curve is y = x
        if d > best_d:
            best_i, best_d = i, d
    return best_i


def max_sustainable(
    rates: Sequence[float],
    p99s_ms: Sequence[Optional[float]],
    target_ms: float,
) -> Optional[float]:
    """Highest offered rate whose p99 stayed at/under ``target_ms``
    (steps with no p99 — nothing measured — don't qualify)."""
    best = None
    for r, p in zip(rates, p99s_ms):
        if p is not None and p <= target_ms:
            best = max(best, float(r)) if best is not None else float(r)
    return best


# -- sweep orchestration ----------------------------------------------------

def run_sweep(
    obs: FleetObserver,
    fire_step: Callable[[float], Dict[str, Any]],
    rates: Sequence[float],
    profile_topn: int = 15,
    flame_acc: Optional[Dict[str, int]] = None,
) -> List[Dict[str, Any]]:
    """Step the offered rate up the ladder: scrape → fire → scrape,
    attach the windowed per-stage decomposition (wall AND cpu), the
    queue-gauge peaks, the window's process-CPU burn, and the window's
    profiler attribution (top functions + per-thread samples) to
    whatever the step driver returned.  ``fire_step(rate)`` runs one
    open-loop step to completion (including its drain grace, so the
    closing scrape sees the step's replies) and returns its client-side
    record (offered/achieved rate, client p50/p99, drops).

    ``flame_acc`` (mutated in place when given) accumulates the merged
    fleet flame across every step — the whole-sweep flamegraph the
    loadcurve CLI writes next to the round file.  The profiler is
    drained once before the ladder so step 1's window excludes warmup."""
    steps: List[Dict[str, Any]] = []
    before = scrape_hists(obs)
    obs.profile_all()  # drain: the ladder starts with a clean window
    obs.tail_all()     # ditto for the tail-exemplar stores
    for rate in rates:
        res = dict(fire_step(float(rate)))
        after = scrape_hists(obs)
        win = window_hists(before, after)
        prof = profile_window(obs, topn=profile_topn)
        res["offered_rate"] = float(rate)
        res["stages"] = stage_stats(win)
        res["cpu"] = cpu_stage_stats(win)
        res["gauges"] = gauge_peaks(after)
        res["proc_cpu_s"] = window_proc_cpu_s(before, after)
        tails = tail_window(obs, p99_ms=res.get("client_p99_ms"))
        if tails is not None:
            res["tail"] = tails
        if flame_acc is not None:
            for k, v in prof.pop("flame").items():
                flame_acc[k] = flame_acc.get(k, 0) + v
        else:
            prof.pop("flame")
        res["profile"] = prof
        steps.append(res)
        before = after  # next step's window starts where this ended
    return steps


def build_loadcurve(
    steps: Sequence[Dict[str, Any]],
    p99_target_ms: float = 50.0,
) -> Dict[str, Any]:
    """Fold the per-step records into the final load-curve report:
    the throughput-vs-latency curve, the detected knee, and the max
    sustainable rate at the p99 target — the JSON body of
    ``LOADCURVE_r*.json`` (metadata added by the caller)."""
    rates = [s["offered_rate"] for s in steps]
    p99s = [s.get("client_p99_ms") for s in steps]
    achieved = [s.get("achieved_ops_per_sec") for s in steps]
    knee_i = find_knee(
        rates, [p if p is not None else 0.0 for p in p99s]
    )
    knee = None
    if knee_i is not None:
        knee = {
            "offered_rate": rates[knee_i],
            "achieved_ops_per_sec": achieved[knee_i],
            "client_p99_ms": p99s[knee_i],
            "index": knee_i,
        }
    sustainable = max_sustainable(rates, p99s, p99_target_ms)
    out = {
        "steps": list(steps),
        "curve": {
            "offered_rate": rates,
            "achieved_ops_per_sec": achieved,
            "client_p50_ms": [s.get("client_p50_ms") for s in steps],
            "client_p99_ms": p99s,
            "client_p999_ms": [s.get("client_p999_ms") for s in steps],
        },
        "knee": knee,
        # Flat mirrors of the headline numbers, so the trajectory gate
        # (scripts/bench_compare.py --family loadcurve) reads them with
        # the same top-level-key lookup as every other family.
        "knee_ops_per_sec": knee["offered_rate"] if knee else None,
        "p99_at_knee_ms": knee["client_p99_ms"] if knee else None,
        "p99_target_ms": p99_target_ms,
        "max_sustainable_ops_per_sec": sustainable,
    }
    if knee_i is not None:
        # Tail-microscope headline columns at the comparable operating
        # point: the extreme tail (p99.9) at the knee, and which queue
        # wait dominated the knee step's retained tail exemplars
        # (tail.py attribution).  Absent in pre-tail rounds → n/a in
        # the gate, never a regression.
        p999 = steps[knee_i].get("client_p999_ms")
        if p999 is not None:
            out["p999_at_knee_ms"] = p999
        dom = (steps[knee_i].get("tail") or {}).get("dominant")
        if dom is not None:
            out["tail_dominant_wait"] = dom
    # CPU-attribution headline columns (bench_compare --family cpu):
    # per-stage CPU-µs per acknowledged op at the KNEE step — the
    # comparable operating point — plus the profiler's top functions
    # at the knee and at saturation (the top of the ladder).  Absent
    # in pre-profiling rounds → n/a in the gate, never a regression.
    if knee_i is not None:
        ks = steps[knee_i]
        ok = ks.get("ok") or 0
        total_us = 0.0
        for stage, rec in (ks.get("cpu") or {}).items():
            if ok and isinstance(rec.get("cpu_s"), (int, float)):
                us = 1e6 * float(rec["cpu_s"]) / ok
                out[f"cpu_{stage}_us_per_op"] = round(us, 2)
                total_us += us
        if ok and total_us:
            out["cpu_total_us_per_op"] = round(total_us, 2)
        out["top_funcs_at_knee"] = (
            (ks.get("profile") or {}).get("top") or []
        )[:5]
    if steps:
        out["top_funcs_at_saturation"] = (
            (steps[-1].get("profile") or {}).get("top") or []
        )[:5]
    return out
