"""Test harnesses: sim-backend drivers (cluster.py, kv_harness.py,
ctrler_harness.py), the real-socket nemesis (nemesis.py), and the
fleet observability scraper (observe.py)."""

from .bundle import collect_bundle
from .nemesis import (
    ChaosClient,
    Nemesis,
    NemesisVerificationError,
    make_schedule,
    run_clerk_load,
)
from .observe import FleetObserver

__all__ = [
    "ChaosClient",
    "FleetObserver",
    "Nemesis",
    "NemesisVerificationError",
    "collect_bundle",
    "make_schedule",
    "run_clerk_load",
]
