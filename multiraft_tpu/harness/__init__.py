"""Test harnesses: sim-backend drivers (cluster.py, kv_harness.py,
ctrler_harness.py) and the real-socket nemesis (nemesis.py)."""

from .nemesis import ChaosClient, Nemesis, make_schedule, run_clerk_load

__all__ = ["ChaosClient", "Nemesis", "make_schedule", "run_clerk_load"]
