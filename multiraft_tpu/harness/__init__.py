"""Test harnesses: sim-backend drivers (cluster.py, kv_harness.py,
ctrler_harness.py), the real-socket nemesis (nemesis.py), the fleet
observability scraper (observe.py), and the load-curve aggregator +
knee finder over open-loop sweeps (loadcurve.py)."""

from .bundle import collect_bundle
from .loadcurve import build_loadcurve, find_knee, max_sustainable, run_sweep
from .nemesis import (
    ChaosClient,
    Nemesis,
    NemesisVerificationError,
    make_schedule,
    run_clerk_load,
)
from .observe import FleetObserver

__all__ = [
    "ChaosClient",
    "FleetObserver",
    "Nemesis",
    "NemesisVerificationError",
    "build_loadcurve",
    "collect_bundle",
    "find_knee",
    "make_schedule",
    "max_sustainable",
    "run_clerk_load",
    "run_sweep",
]
