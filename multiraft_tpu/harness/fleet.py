"""Placed-fleet harness: an :class:`~multiraft_tpu.distributed.
engine_cluster.EngineFleetCluster` with the placement controller wired
on top (ARCHITECTURE §14).

Two pieces:

* :class:`PlacementMap` — the Raft-replicated placement map as a
  blocking facade.  The map itself is a sim-substrate cluster of
  :class:`~multiraft_tpu.distributed.placement.PlacementCtrler`
  replicas (same Scheduler/Network machinery as every other sim RSM
  in the repo); all sim activity is pumped on whichever caller thread
  holds the lock, via ``run_until(spawn(clerk_gen))``.  Killing the
  map's current leader (``kill_leader``) and watching the controller
  keep working is the "survives its own leader dying" test.

* :class:`PlacedFleet` — fleet processes (started with spare engine
  slots for adoption) + the map + a
  :class:`~multiraft_tpu.distributed.placement.PlacementController`
  thread scraping them over a dedicated
  :class:`~multiraft_tpu.distributed.tcp.RpcNode`.
  ``kill_mesh_process`` is the chaos verb: SIGKILL one process and let
  the controller's failure detector re-place its groups onto
  survivors (empty adoption — the fleet crash model, see
  distributed/placement.py's module docstring).

Plus the in-process form: :class:`InProcessFleet` (several
:class:`~multiraft_tpu.engine.shardkv.BatchedShardKV` instances
sharing one gid space, remote hooks wired directly) and
:class:`LocalFleetTransport` (the controller's transport duck type
over those instances) — the deterministic, socket-free substrate the
tier-1 placement tests and ``scripts/placement_scenario.py`` run on.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..distributed.placement import (
    PlacementClerk,
    PlacementController,
    PlacementCtrler,
    TcpFleetTransport,
)
from ..sim.scheduler import Scheduler
from ..transport.network import Network
from .cluster import Cluster

__all__ = [
    "PlacementMap",
    "PlacedFleet",
    "InProcessFleet",
    "InProcFleetClerk",
    "LocalFleetTransport",
]


class PlacementMap:
    """Blocking facade over the replicated placement map (module
    docstring).  Verbs mirror the controller's ``store`` duck type:
    ``query / set_map / begin / commit / abort``."""

    def __init__(self, n: int = 3, seed: int = 0,
                 initial: Optional[Dict[int, int]] = None) -> None:
        self.sched = Scheduler()
        self.net = Network(self.sched, seed=seed)
        self.net.set_reliable(True)
        self.n = n

        def factory(ends, i, persister, srv_seed):
            srv = PlacementCtrler(
                self.sched, ends, i, persister, seed=srv_seed
            )
            return srv, {"Placement": srv, "Raft": srv.rf}

        self.cluster = Cluster(
            self.sched, self.net, "plc", n, factory,
            random.Random(seed ^ 0x9A7), seed=seed,
        )
        self.cluster.start_all()
        self._lock = threading.Lock()
        self._clerk = PlacementClerk(
            self.sched, self.cluster.make_client_ends()
        )
        if initial:
            self.set_map(initial)

    def _run(self, gen):
        # One lock around all sim pumping: the controller thread and
        # the test thread both drive this scheduler, never concurrently.
        with self._lock:
            return self.sched.run_until(self.sched.spawn(gen))

    # -- store verbs ----------------------------------------------------

    def query(self):
        r = self._run(self._clerk.query())
        return (
            r.version, dict(r.placement), dict(r.pending), list(r.history)
        )

    def set_map(self, placement: Dict[int, int]) -> int:
        return self._run(self._clerk.set_map(placement)).version

    def begin(self, gid: int, dst: int, reason: str) -> None:
        self._run(self._clerk.begin(gid, dst, reason))

    def dispatch(self, gid: int) -> None:
        self._run(self._clerk.dispatch(gid))

    def commit(self, gid: int) -> int:
        return self._run(self._clerk.commit(gid)).version

    def abort(self, gid: int) -> None:
        self._run(self._clerk.abort(gid))

    # -- reconfig intents (replace-dead-replica policy) ------------------

    def reconfig_intents(self) -> Dict[int, Tuple[int, int, str]]:
        return dict(self._run(self._clerk.query()).reconfigs)

    def rbegin(self, gid: int, dead_peer: int, new_peer: int) -> None:
        self._run(self._clerk.rbegin(gid, dead_peer, new_peer))

    def rphase(self, gid: int, phase: str) -> None:
        self._run(self._clerk.rphase(gid, phase))

    def rdone(self, gid: int) -> None:
        self._run(self._clerk.rdone(gid))

    def rabort(self, gid: int) -> None:
        self._run(self._clerk.rabort(gid))

    # -- chaos ----------------------------------------------------------

    def leader(self) -> Optional[int]:
        for i, h in enumerate(self.cluster.handles):
            if h is None:
                continue
            _, is_leader = h.rf.get_state()
            if is_leader:
                return i
        return None

    def kill_leader(self) -> Optional[int]:
        """Shut down the map's current leader replica; the next store
        verb pumps the survivors through an election."""
        with self._lock:
            lead = None
            for i, h in enumerate(self.cluster.handles):
                if h is not None and h.rf.get_state()[1]:
                    lead = i
                    break
            if lead is not None:
                self.cluster.shutdown_server(lead)
            return lead

    def restart_replica(self, i: int) -> None:
        with self._lock:
            self.cluster.start_server(i)

    def cleanup(self) -> None:
        self.cluster.kill_all()
        self.net.cleanup()


class PlacedFleet:
    """Fleet + map + controller, one lifecycle (module docstring)."""

    def __init__(
        self,
        assignment: Sequence[Sequence[int]],
        *,
        spare_slots: int = 2,
        seed: int = 0,
        ctrl_replicas: int = 3,
        host: str = "127.0.0.1",
        mesh_devices: int = 0,
        chaos_seed: Optional[int] = None,
        controller_kwargs: Optional[dict] = None,
        shipping: bool = False,
        ship_sync: Optional[bool] = None,
        ship_window_s: Optional[float] = None,
        data_dir: Optional[str] = None,
        replicas: int = 3,
        voters: Optional[Sequence[int]] = None,
    ) -> None:
        from ..distributed.engine_cluster import EngineFleetCluster

        # Sync shipping gates acks through EngineDurability's
        # extra_sync_gate — without a WAL there is no ack gate to hang
        # it on, and "zero acknowledged-write loss" would silently not
        # hold.  Provision a data_dir rather than no-op the guarantee.
        self._own_data_dir = None
        if ship_sync and data_dir is None:
            import tempfile

            data_dir = self._own_data_dir = tempfile.mkdtemp(
                prefix="mrt-placed-fleet-"
            )
        self.cluster = EngineFleetCluster(
            assignment, host=host, seed=seed, spare_slots=spare_slots,
            mesh_devices=mesh_devices, chaos_seed=chaos_seed,
            shipping=shipping, ship_sync=ship_sync,
            ship_window_s=ship_window_s, data_dir=data_dir,
            replicas=replicas, voters=voters,
        )
        self.ctrl_replicas = ctrl_replicas
        self.seed = seed
        self._controller_kwargs = dict(controller_kwargs or {})
        self.pmap: Optional[PlacementMap] = None
        self.controller: Optional[PlacementController] = None
        self.node = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        from ..distributed.tcp import RpcNode

        self.cluster.start_all()
        initial = {
            g: i
            for i, gl in enumerate(self.cluster.assignment)
            for g in gl
        }
        self.pmap = PlacementMap(
            n=self.ctrl_replicas, seed=self.seed ^ 0x51A,
            initial=initial,
        )
        self.node = RpcNode()
        transport = TcpFleetTransport(
            self.node,
            [(self.cluster.host, p) for p in self.cluster.ports],
        )
        self.controller = PlacementController(
            transport, self.pmap, obs=self.node.obs,
            **self._controller_kwargs,
        )
        self.controller.start()

    def shutdown(self) -> None:
        if self.controller is not None:
            self.controller.stop()
            self.controller = None
        if self.node is not None:
            self.node.close()
            self.node = None
        if self.pmap is not None:
            self.pmap.cleanup()
            self.pmap = None
        self.cluster.shutdown()
        if self._own_data_dir is not None:
            import shutil

            shutil.rmtree(self._own_data_dir, ignore_errors=True)
            self._own_data_dir = None

    # -- surface ---------------------------------------------------------

    def clerk(self):
        return self.cluster.clerk()

    def admin(self, kind: str, arg, timeout: float = 60.0) -> None:
        self.cluster.admin(kind, arg, timeout=timeout)

    def placement(self) -> Tuple[int, Dict[int, int]]:
        version, placement, _, _ = self.pmap.query()
        return version, placement

    def history(self) -> List[Tuple[int, int, int, int, str]]:
        return self.pmap.query()[3]

    def kill_mesh_process(self, i: int) -> None:
        """SIGKILL fleet process ``i``.  Its groups go dark until the
        controller's ``dead_s`` deadline fires and re-places them onto
        survivors; the process stays dead (never restarted by the
        placement layer)."""
        self.cluster.kill(i)

    def kill_replica(self, gid: int, peer: int) -> bool:
        """Permanently kill ONE engine replica of ``gid`` at its
        current owner process (the process lives on) — the fault the
        controller's replace-dead-replica policy heals via joint
        consensus.  Routed through the controller's own transport."""
        tr = self.controller.transport
        _, placement = self.placement()
        proc = placement.get(gid)
        return proc is not None and tr.kill_replica(proc, gid, peer)


# ---------------------------------------------------------------------------
# In-process fleet (deterministic, socket-free)
# ---------------------------------------------------------------------------


class InProcessFleet:
    """Several :class:`~multiraft_tpu.engine.shardkv.BatchedShardKV`
    instances sharing one global gid space — the in-process analog of
    an :class:`~multiraft_tpu.distributed.engine_cluster.
    EngineFleetCluster`, with the shard-migration hooks wired directly
    between instances (same gating as the networked service) but
    placement-aware: the owner lookup follows groups as the controller
    moves them, and a killed instance's hooks answer like a dead
    process (no replies, ever)."""

    def __init__(
        self,
        assignment: Sequence[Sequence[int]],
        spare_slots: int = 1,
        seed: int = 0,
        replicas: int = 3,
        voters: Optional[Sequence[int]] = None,
    ) -> None:
        from ..engine.core import EngineConfig
        from ..engine.host import EngineDriver
        from ..engine.shardkv import BatchedShardKV

        self.assignment = [list(g) for g in assignment]
        self.instances: List[Any] = []
        self.killed: set = set()
        # State-plane wiring (enable_shipping): proc -> StatePlane /
        # StandbyStore.  Empty = shipping off (the default crash model).
        self.planes: Dict[int, Any] = {}
        self.standbys: Dict[int, Any] = {}
        for i, gl in enumerate(self.assignment):
            cfg = EngineConfig(
                G=len(gl) + 1 + spare_slots, P=replicas, L=64, E=8,
                INGEST=8,
            )
            driver = EngineDriver(cfg, seed=seed + 131 * i)
            if voters is not None and len(set(voters)) < replicas:
                # Spare ENGINE REPLICA slots (self-healing replica
                # sets): only ``voters`` vote, the remaining rows park
                # dead until the placement controller seats a learner
                # in one to replace a permanently killed voter.
                driver.seed_config(voters)
            if not driver.run_until_quiet_leaders(max_ticks=2000):
                raise RuntimeError(f"instance {i} leaders never settled")
            self.instances.append(BatchedShardKV(driver, gids=gl))
        self._wire()

    def owner_of(self, gid: int):
        """The live instance hosting ``gid`` right now (placement-aware,
        unlike the static map in tests/test_engine_fleet.py)."""
        for p, inst in enumerate(self.instances):
            if p in self.killed:
                continue
            if gid in inst._g2l:
                return inst
        return None

    def proc_of(self, gid: int) -> Optional[int]:
        for p, inst in enumerate(self.instances):
            if p not in self.killed and gid in inst._g2l:
                return p
        return None

    def _wire(self) -> None:
        fleet = self
        for inst in self.instances:
            pending: Dict[tuple, Any] = {}

            def remote_fetch(src_gid, shard, num, _me=inst):
                peer = fleet.owner_of(src_gid)
                if peer is None or peer is _me:
                    return None
                rep = peer.reps.get(src_gid)
                if rep is None or rep.cur.num < num:
                    return None  # ErrNotReady
                return (
                    dict(rep.shards[shard].data),
                    dict(rep.shards[shard].latest),
                )

            def remote_delete(src_gid, shard, num, _pending=pending):
                from ..engine.shardkv import OK

                peer = fleet.owner_of(src_gid)
                if peer is None:
                    return True  # dead or dropped: nothing to delete
                key = (src_gid, shard, num)
                t = _pending.get(key)
                if t is None:
                    _pending[key] = peer.delete_shard(src_gid, shard, num)
                    return None
                if not t.done:
                    return None
                del _pending[key]
                return (not t.failed) and t.err == OK

            inst.remote_fetch = remote_fetch
            inst.remote_delete = remote_delete

    # -- state plane -----------------------------------------------------

    def enable_shipping(
        self,
        rules=None,
        *,
        window_s: Optional[float] = None,
        tail_cap: Optional[int] = None,
        sync: bool = False,
        labels: Optional[Dict[int, str]] = None,
        obs=None,
    ) -> Dict[int, Any]:
        """Wire a :class:`~multiraft_tpu.distributed.stateplane.
        StatePlane` shipper and a ``StandbyStore`` receiver onto every
        instance; delivery is a direct call into the standby's store
        (dead standbys answer ``None``, like a dead process).  Shipping
        runs inside :meth:`pump_all`, so any test that pumps the fleet
        ships for free."""
        from ..distributed.stateplane import StandbyStore, StatePlane

        fleet = self
        self.standbys = {
            p: StandbyStore(obs=obs) for p in range(len(self.instances))
        }

        def send(sb: int, payload: bytes):
            if sb in fleet.killed:
                return None
            return fleet.standbys[sb].receive(payload)

        for p, inst in enumerate(self.instances):
            plane = StatePlane(
                inst, me=p, n_procs=len(self.instances), send=send,
                rules=rules, labels=labels, window_s=window_s,
                tail_cap=tail_cap, sync=sync, obs=obs,
            )
            plane.attach()
            self.planes[p] = plane
        return self.planes

    # -- fleet ops -------------------------------------------------------

    def admin(self, kind: str, arg) -> None:
        """Mirror one config op to every live instance (same order →
        identical config histories)."""
        for p, inst in enumerate(self.instances):
            if p not in self.killed:
                inst.admin_sync(kind, arg)

    def pump_all(self, n: int = 5) -> None:
        for p, inst in enumerate(self.instances):
            if p not in self.killed:
                inst.pump(n)
                plane = self.planes.get(p)
                if plane is not None:
                    plane.ship_round()

    def settle(self, max_rounds: int = 800) -> None:
        from ..services.shardkv import SERVING

        live = [
            inst for p, inst in enumerate(self.instances)
            if p not in self.killed
        ]
        target = live[0].query_latest().num
        for _ in range(max_rounds):
            self.pump_all()
            done = True
            for inst in live:
                cfg = inst.query_latest()
                for g in list(inst.gids):
                    if g not in cfg.groups or inst.is_sealed(g):
                        continue
                    rep = inst.reps[g]
                    if rep.cur.num != target or any(
                        sh.state != SERVING
                        for sh in rep.shards.values()
                    ):
                        done = False
            if done:
                return
        raise TimeoutError(f"fleet did not settle at config {target}")

    def kill(self, p: int) -> None:
        """Mark instance ``p`` dead: no more pumps, its hooks stop
        answering, its memory is never read again (the crash model)."""
        self.killed.add(p)

    def kill_replica(self, gid: int, peer: int) -> bool:
        """Chaos verb: permanently kill ONE engine replica of ``gid``
        (the process lives; the replica row never ticks again) — the
        fault the controller's replace-dead-replica policy heals."""
        inst = self.owner_of(gid)
        return inst is not None and inst.kill_replica_gid(gid, peer)

    def clerk(self, client_id: int = 1) -> "InProcFleetClerk":
        return InProcFleetClerk(self, client_id=client_id)


class InProcFleetClerk:
    """Cross-instance clerk with LIVE routing: key → shard → gid from
    the latest config, gid → instance from the fleet's current
    placement (retrying ErrWrongGroup, so it follows migrations the
    same way the socket clerk's placement refresh does)."""

    def __init__(self, fleet: InProcessFleet, client_id: int = 1) -> None:
        self.fleet = fleet
        self.client_id = client_id
        self.command_id = 0

    def _run(self, op: str, key: str, value: str = ""):
        from ..engine.shardkv import ERR_WRONG_GROUP
        from ..services.shardkv import key2shard

        if op != "Get":
            self.command_id += 1
        fleet = self.fleet
        for _ in range(600):
            live = [
                i for p, i in enumerate(fleet.instances)
                if p not in fleet.killed
            ]
            if not live:
                break
            cfg = live[0].query_latest()
            gid = cfg.shards[key2shard(key)]
            inst = fleet.owner_of(gid)
            if inst is None or inst.is_sealed(gid):
                fleet.pump_all(2)
                continue
            t = inst.submit(
                gid, op, key, value,
                client_id=self.client_id, command_id=self.command_id,
            )
            if t is None:
                fleet.pump_all(2)
                continue
            waited = 0
            while not t.done and waited < 400:
                fleet.pump_all(2)
                waited += 2
            if t.done and not t.failed and t.err != ERR_WRONG_GROUP:
                return t
        raise TimeoutError(f"{op}({key!r}) never served")

    def get(self, key: str) -> str:
        from ..engine.shardkv import OK

        t = self._run("Get", key)
        return t.value if t.err == OK else ""

    def put(self, key: str, value: str) -> None:
        self._run("Put", key, value)

    def append(self, key: str, value: str) -> None:
        self._run("Append", key, value)


class LocalFleetTransport:
    """The controller's fleet-transport duck type
    (distributed/placement.py) over an :class:`InProcessFleet` —
    synchronous, deterministic, no sockets.  ``groups()`` computes the
    same windowed commit rates ``Obs.groups`` serves, from each
    driver's commit frontier between scrapes."""

    def __init__(self, fleet: InProcessFleet) -> None:
        self.fleet = fleet
        # proc -> (t_prev_s, commit list) of the previous scrape.
        self._prev: Dict[int, Tuple[float, List[int]]] = {}

    @property
    def n_procs(self) -> int:
        return len(self.fleet.instances)

    def addr(self, proc: int) -> Tuple[str, int]:
        return ("inproc", proc)

    def ping(self, proc: int) -> bool:
        return proc not in self.fleet.killed

    def groups(self, proc: int) -> Optional[Dict[str, Any]]:
        import numpy as np

        if proc in self.fleet.killed:
            return None
        inst = self.fleet.instances[proc]
        G = inst.driver.cfg.G
        commit = [
            int(c)
            for c in np.asarray(
                inst.driver.last_metrics["commit_index"]
            ).tolist()
        ]
        now = time.perf_counter()
        prev = self._prev.get(proc)
        if prev is None or len(prev[1]) != G or now <= prev[0]:
            rate = [0.0] * G
        else:
            dt = now - prev[0]
            rate = [
                max(0.0, (c - p) / dt) for c, p in zip(commit, prev[1])
            ]
        self._prev[proc] = (now, commit)
        gids = [inst._l2g.get(g, -1) for g in range(G)]
        out = {
            "G": G,
            "gids": gids,
            "commit": commit,
            "commit_rate": rate,
        }
        # Membership columns (mirror Obs.groups): per-replica liveness,
        # the voter union, and the reconfig/sealed exemption flags the
        # controller's healer and the wedge watch consume.
        from ..engine.core import LEADER

        st = inst.driver.np_state()
        vo = st.get("voters_old")
        if vo is not None:
            vn = st["voters_new"]
            joint = st["joint"]
            cfg_idx = st["cfg_idx"]
            alive = st["alive"]
            lead = (st["role"] == LEADER) & alive
            P = int(vo.shape[1])
            union = vo | vn
            row = np.where(
                lead.any(axis=1), lead.argmax(axis=1), union.argmax(axis=1)
            )
            bits = union[np.arange(G), row]
            out["replica_alive"] = alive.tolist()
            out["voters"] = [
                [q for q in range(P) if (int(b) >> q) & 1] for b in bits
            ]
            out["joint"] = joint.any(axis=1).tolist()
            out["reconfig"] = (
                joint.any(axis=1)
                | (cfg_idx.max(axis=1) > np.asarray(commit))
            ).tolist()
        out["sealed"] = [
            bool(gids[g] > 0 and inst.is_sealed(gids[g])) for g in range(G)
        ]
        return out

    def pull_group(self, proc: int, gid: int):
        if proc in self.fleet.killed:
            return None
        inst = self.fleet.instances[proc]
        if gid not in inst._g2l:
            return None
        return inst.export_group(gid)

    def unseal_group(self, proc: int, gid: int,
                     force: bool = False) -> None:
        if proc not in self.fleet.killed:
            self.fleet.instances[proc].unseal_group(gid, force)

    def adopt_group(self, proc: int, gid: int, blob) -> bool:
        if proc in self.fleet.killed:
            return False
        inst = self.fleet.instances[proc]
        if gid in inst.reps:
            return True  # idempotent retry
        if inst.free_slots() < 1:
            return False
        inst.adopt_gid(gid, blob)
        return True

    def drop_group(self, proc: int, gid: int) -> bool:
        if proc in self.fleet.killed:
            return True  # dead: its slots died with it
        inst = self.fleet.instances[proc]
        if gid not in inst._g2l:
            return True
        for _ in range(400):
            if inst.group_quiesced(gid):
                inst.drop_gid(gid)
                plane = self.fleet.planes.get(proc)
                if plane is not None:
                    plane.forget_group(gid)
                return True
            inst.pump(2)
        return False

    # -- state plane (distributed/stateplane.py) -------------------------

    def standby_state(self, proc: int, gid: int):
        """The standby's shipped-state freshness for ``gid`` (None when
        the proc is dead, shipping is off, or it holds nothing) — the
        controller's ``_freshest_dst`` probe."""
        if proc in self.fleet.killed:
            return None
        store = self.fleet.standbys.get(proc)
        return store.freshness(gid) if store is not None else None

    def recover_group(self, proc: int, gid: int) -> Optional[str]:
        """Stateful failover leg: adopt ``gid`` on ``proc`` from its
        shipped snapshot+tail.  Returns ``"recovered"`` on success,
        ``"empty"`` when no shipped state exists (the controller falls
        back to explicit empty adoption), ``None`` on transient
        failure (retry next sweep)."""
        from ..distributed.stateplane import recovery_blob, replay_tail

        fleet = self.fleet
        if proc in fleet.killed:
            return None
        store = fleet.standbys.get(proc)
        held = store.get(gid) if store is not None else None
        if held is None:
            return "empty"
        snap, tail = held
        inst = fleet.instances[proc]
        if gid not in inst.reps:
            blob = recovery_blob(snap, inst.query_latest())
            if blob is None and not tail:
                return "empty"
            if inst.free_slots() < 1:
                return None
            inst.adopt_gid(gid, blob)
        if tail:
            # Re-submit through the group's own log with the original
            # session ids — dedup (restored from the snapshot) makes a
            # repeated attempt exactly-once.
            replay_tail(inst, gid, tail,
                        pump=lambda: fleet.pump_all(2))
        store.drop(gid)
        return "recovered"

    def push_placement(self, proc: int, version: int, addr_map) -> bool:
        # In-process routing is live (owner_of), so there is no peer
        # map to rebuild — recording the push keeps the controller's
        # contract observable for tests.
        self.last_push = (version, dict(addr_map))
        return proc not in self.fleet.killed

    # -- membership-change verbs (self-healing replica sets) -------------

    def _inst(self, proc: int):
        if proc in self.fleet.killed:
            return None
        return self.fleet.instances[proc]

    def replica_config(self, proc: int, gid: int):
        inst = self._inst(proc)
        return None if inst is None else inst.config_of_gid(gid)

    def add_learner(self, proc: int, gid: int, peer: int) -> bool:
        inst = self._inst(proc)
        return inst is not None and inst.add_learner_gid(gid, peer)

    def learner_match(self, proc: int, gid: int, peer: int):
        inst = self._inst(proc)
        return None if inst is None else inst.learner_match_gid(gid, peer)

    def begin_joint(self, proc: int, gid: int, voters) -> bool:
        inst = self._inst(proc)
        return inst is not None and inst.begin_joint_gid(gid, voters)

    def kill_replica(self, proc: int, gid: int, peer: int) -> bool:
        inst = self._inst(proc)
        return inst is not None and inst.kill_replica_gid(gid, peer)
