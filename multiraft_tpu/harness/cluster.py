"""Generic replicated-service cluster fixture.

One :class:`Cluster` manages n replicas of a Raft-backed service inside
a (possibly shared) simulated network — the common machinery behind the
kvraft, shardctrler, and shardkv harnesses (reference: the parallel
``config.go`` files in kvraft/, shardctrler/, shardkv/; the shardkv
harness builds one controller cluster plus several KV group clusters in
a single network, shardkv/config.go:338-382).

Server names are ``(tag, i)``; endpoint names are incarnation-fresh so
crash/restart leaves zombie instances whose replies can never land.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional

from ..raft.persister import Persister
from ..sim.scheduler import Scheduler
from ..transport.network import ClientEnd, Network, Server, Service

__all__ = ["Cluster"]

# factory(ends, i, persister, seed) -> (handle, {service_name: obj})
Factory = Callable[[List[ClientEnd], int, Persister, int], tuple]


class Cluster:
    def __init__(
        self,
        sched: Scheduler,
        net: Network,
        tag: Any,
        n: int,
        factory: Factory,
        rng: random.Random,
        seed: int = 0,
    ) -> None:
        self.sched = sched
        self.net = net
        self.tag = tag
        self.n = n
        self.factory = factory
        self.rng = rng
        self.seed = seed
        self.handles: List[Optional[Any]] = [None] * n
        self.saved: List[Persister] = [Persister() for _ in range(n)]
        self.endnames: List[List[Any]] = [[None] * n for _ in range(n)]
        self.groups = [0] * n  # partition side per server
        self._incarnation = 0
        self._next_clerk = 0
        self.clerk_endnames: Dict[Any, List[Any]] = {}

    def server_name(self, i: int) -> Any:
        return (self.tag, i)

    # -- lifecycle --------------------------------------------------------

    def start_server(self, i: int) -> Any:
        if self.handles[i] is not None:
            self.shutdown_server(i)
        self._incarnation += 1
        inc = self._incarnation
        ends = []
        for j in range(self.n):
            name = (self.tag, i, j, inc)
            self.endnames[i][j] = name
            end = self.net.make_end(name)
            self.net.connect(name, self.server_name(j))
            ends.append(end)
        persister = self.saved[i].copy()
        self.saved[i] = persister
        handle, services = self.factory(
            ends, i, persister, self.seed * 977 + inc
        )
        self.handles[i] = handle
        server = Server()
        for svc_name, obj in services.items():
            server.add_service(Service(obj, name=svc_name))
        self.net.add_server(self.server_name(i), server)
        self._apply_edges()
        return handle

    def shutdown_server(self, i: int) -> None:
        self.net.delete_server(self.server_name(i))
        self.saved[i] = self.saved[i].copy()
        if self.handles[i] is not None:
            self.handles[i].kill()
            self.handles[i] = None

    def start_all(self) -> None:
        for i in range(self.n):
            self.start_server(i)
        self.connect_all()

    def kill_all(self) -> None:
        for h in self.handles:
            if h is not None:
                h.kill()

    # -- connectivity -----------------------------------------------------

    def _apply_edges(self) -> None:
        for i in range(self.n):
            for j in range(self.n):
                if self.endnames[i][j] is not None:
                    self.net.enable(
                        self.endnames[i][j], self.groups[i] == self.groups[j]
                    )

    def connect_all(self) -> None:
        self.groups = [0] * self.n
        self._apply_edges()

    def partition(self, p1: List[int], p2: List[int]) -> None:
        for i in p1:
            self.groups[i] = 0
        for i in p2:
            self.groups[i] = 1
        self._apply_edges()

    def random_partition(self) -> None:
        p1, p2 = [], []
        for i in range(self.n):
            (p1 if self.rng.random() < 0.5 else p2).append(i)
        self.partition(p1, p2)

    # -- clients ----------------------------------------------------------

    def make_client_ends(
        self, owner: Any = None, shuffle: bool = True
    ) -> List[ClientEnd]:
        """Endpoints from a fresh client to every server in this cluster
        (shuffled order exercises leader search)."""
        self._next_clerk += 1
        cid = (self.tag, "ck", self._next_clerk, owner)
        order = list(range(self.n))
        if shuffle:
            self.rng.shuffle(order)
        ends, names = [], []
        for j in order:
            name = (cid, j)
            end = self.net.make_end(name)
            self.net.connect(name, self.server_name(j))
            self.net.enable(name, True)
            ends.append(end)
            names.append(name)
        self.clerk_endnames[cid] = names
        self._last_clerk_id = cid
        return ends

    def restrict_client(self, cid: Any, to: List[int]) -> None:
        allowed = set(to)
        for name in self.clerk_endnames[cid]:
            _, j = name
            self.net.enable(name, j in allowed)

    # -- queries ----------------------------------------------------------

    def current_leader(self) -> int:
        best, best_term = -1, -1
        for i, h in enumerate(self.handles):
            if h is not None:
                term, is_leader = h.rf.get_state()
                if is_leader and term > best_term:
                    best, best_term = i, term
        return best

    def log_size(self) -> int:
        return max(p.raft_state_size() for p in self.saved)

    def snapshot_size(self) -> int:
        return max(p.snapshot_size() for p in self.saved)
