"""Nemesis: seeded fault schedules against live engine process
clusters.

The sim backend is routinely tested under labrpc-style faults; this
module brings the same discipline to the deployment path.  A
:func:`make_schedule` call turns ``(seed, n_procs)`` into a
deterministic timeline of fault windows — delay storms, drop storms
(requests AND replies), pair partitions, full isolation, mid-stream
connection severs, crash + restart-from-WAL/checkpoint, open-loop
load surges (the admission-control stressor) — and
:class:`Nemesis` executes it against a running cluster through the
servers' ``"Chaos"`` control RPC (distributed/chaos.py), while
:func:`run_clerk_load` applies concurrent blocking-clerk traffic and
collects the porcupine history that proves the fleet stayed
linearizable through it all.

Determinism: the schedule is a pure function of its arguments — the
acceptance bar "the same seed reproduces the same fault schedule" is
``make_schedule(s, n) == make_schedule(s, n)``, and the runner's
``applied`` log records what was actually executed.  (Per-frame coin
flips inside each server draw from the server's own seeded RNG and
depend on traffic order; the *windows* — what faults, where, when —
are exactly reproducible.)

Usage::

    cluster = EngineProcessCluster(..., chaos_seed=7)
    cluster.start()
    sched = make_schedule(seed=7, n_procs=1, duration_s=6.0,
                          include=("delay", "drop", "sever"))
    nem = Nemesis([(cluster.host, cluster.port)])
    t = nem.run_async(sched)
    history = run_clerk_load(cluster.clerk, keys=["a", "b"])
    t.join(); nem.close()
    assert_linearizable(kv_model, history, ...)

Fault windows heal themselves (every storm has a bounded ``dur`` and
the schedule ends with a global heal), so clerk retry loops always
converge — pick per-op timeouts longer than the longest window.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..distributed.chaos import ChaosRule
from ..distributed.observe import now_us
from ..distributed.tcp import RpcNode
from ..sim.scheduler import TIMEOUT
from ..utils.knobs import knob_str

__all__ = [
    "make_schedule",
    "ChaosClient",
    "Nemesis",
    "NemesisVerificationError",
    "run_clerk_load",
]

Addr = Tuple[str, int]
# One schedule entry: (at_seconds, kind, params) — plain data so tests
# can compare whole schedules across runs.
Event = Tuple[float, str, Dict[str, Any]]


def make_schedule(
    seed: int,
    n_procs: int,
    duration_s: float = 8.0,
    include: Sequence[str] = ("delay", "drop", "partition", "sever"),
    crash_procs: Sequence[int] = (),
    crash_down_s: float = 1.0,
    kill_procs: Sequence[int] = (),
    kill_replicas: Sequence[Tuple[int, int]] = (),
    fault_s: Tuple[float, float] = (0.6, 1.8),
    quiet_s: Tuple[float, float] = (0.2, 0.8),
    surge_rate: float = 0.0,
    surge_dur_s: float = 1.5,
    surge_proc: int = 0,
) -> List[Event]:
    """Deterministic fault timeline: alternating fault windows and
    quiet gaps until ``duration_s``, plus one crash+restart per entry
    of ``crash_procs`` (placed in the middle of the run, where traffic
    and chaos overlap it).  Same arguments ⇒ identical schedule.

    ``include`` picks the window kinds: ``delay`` (labrpc's
    unreliable/long-delay mix on a process's inbound frames), ``drop``
    (inbound drops + reply drops — the dedup-exercising case),
    ``partition`` (symmetric pair block, n_procs ≥ 2), ``isolate``
    (one process's inbound fully blocked — the minority case), and
    ``sever`` (cut every live connection once, mid-stream).

    Gray-failure kinds (the faults that wedge fleets without tripping
    fail-stop detectors): ``asym_partition`` (ONE-WAY block a→b —
    b still reaches a; n_procs ≥ 2), ``partial_partition`` (one
    process severed from every OTHER engine process in both
    directions while client traffic still flows — the
    leader-hears-clerks-but-not-quorum case; n_procs ≥ 2),
    ``slow_link`` (deterministic per-frame latency floor on a
    process's inbound frames — degraded-but-alive, not burst jitter),
    and ``fsync_stall`` (every durable write on a process stalls —
    slow-but-alive storage, injected through distributed/disk.py).

    ``surge_rate`` > 0 adds one ``load_surge`` window mid-run: an
    open-loop request burst at that offered rate (ops/s) fired at
    process ``surge_proc`` for ``surge_dur_s`` seconds — the
    admission-control stressor.  The burst rides the nemesis's own
    window ledger, so :meth:`Nemesis.verify_windows` can require that
    the surge demonstrably reached the server (replies came back)
    while the rest of the schedule's faults were live.

    ``kill_procs``: one PERMANENT ``kill_mesh_process`` per entry —
    unlike ``crash``, the process is never restarted; the placement
    controller (distributed/placement.py) is what re-places its groups
    onto survivors.  Keep ``kill_procs`` disjoint from ``crash_procs``
    (a crash's restart would resurrect a process the placement layer
    has already declared dead).

    ``kill_replicas``: one PERMANENT ``kill_replica`` per ``(gid,
    peer)`` entry — the serving process survives but ONE engine
    replica row of group ``gid`` never ticks again.  Recovery is the
    controller's replace-dead-replica policy (learner → catch-up →
    joint entry → promote), not a restart; this is the fault the
    self-healing acceptance runs schedule against clerk load."""
    rng = random.Random(seed)
    _pairwise = ("partition", "asym_partition", "partial_partition")
    kinds = [k for k in include if k not in _pairwise or n_procs > 1]
    events: List[Event] = []
    t = rng.uniform(*quiet_s)
    while t < duration_s and kinds:
        kind = rng.choice(kinds)
        dur = round(rng.uniform(*fault_s), 3)
        i = rng.randrange(n_procs)
        at = round(t, 3)
        if kind == "partition":
            j = rng.choice([x for x in range(n_procs) if x != i])
            events.append((at, "partition", {"a": i, "b": j, "dur": dur}))
        elif kind == "delay":
            events.append((at, "delay_storm", {
                "proc": i, "dur": dur,
                "prob": round(rng.uniform(0.3, 0.9), 3),
                "delay_min": 0.0,
                "delay_max": round(rng.uniform(0.05, 0.4), 3),
            }))
        elif kind == "drop":
            events.append((at, "drop_storm", {
                "proc": i, "dur": dur,
                "prob": round(rng.uniform(0.2, 0.6), 3),
            }))
        elif kind == "isolate":
            events.append((at, "isolate", {"proc": i, "dur": dur}))
        elif kind == "asym_partition":
            j = rng.choice([x for x in range(n_procs) if x != i])
            events.append(
                (at, "asym_partition", {"a": i, "b": j, "dur": dur})
            )
        elif kind == "partial_partition":
            events.append((at, "partial_partition", {"proc": i, "dur": dur}))
        elif kind == "slow_link":
            events.append((at, "slow_link", {
                "proc": i, "dur": dur,
                "floor": round(rng.uniform(0.02, 0.12), 3),
            }))
        elif kind == "fsync_stall":
            events.append((at, "fsync_stall", {
                "proc": i, "dur": dur,
                "stall": round(rng.uniform(0.05, 0.3), 3),
            }))
        elif kind == "sever":
            events.append((at, "sever", {"proc": i}))
        else:
            raise ValueError(f"unknown fault kind {kind!r}")
        t += dur + rng.uniform(*quiet_s)
    for k, proc in enumerate(crash_procs):
        # Mid-run, staggered so two crashes never overlap.
        at = round(duration_s * (0.35 + 0.25 * k / max(1, len(crash_procs))), 3)
        events.append((at, "crash", {"proc": int(proc),
                                     "down": float(crash_down_s)}))
    if surge_rate > 0.0:
        # One open-loop burst, mid-run: overlaps both traffic and any
        # fault windows scheduled around the 40% mark.
        events.append((round(duration_s * 0.4, 3), "load_surge", {
            "proc": int(surge_proc),
            "rate": float(surge_rate),
            "dur": round(float(surge_dur_s), 3),
        }))
    for k, proc in enumerate(kill_procs):
        # Permanent kills land mid-run with traffic and chaos live.
        at = round(
            duration_s * (0.45 + 0.2 * k / max(1, len(kill_procs))), 3
        )
        events.append((at, "kill_mesh_process", {"proc": int(proc)}))
    for k, (gid, peer) in enumerate(kill_replicas):
        # Replica kills land early (~30%) so the whole learner →
        # joint → promote pipeline plays out under the remaining
        # chaos windows and traffic.
        at = round(
            duration_s * (0.3 + 0.2 * k / max(1, len(kill_replicas))), 3
        )
        events.append(
            (at, "kill_replica", {"gid": int(gid), "peer": int(peer)})
        )
    # The global heal comes strictly after every window has closed —
    # it must be the schedule's last executed action.
    end = max(
        [duration_s]
        + [at + p.get("dur", p.get("down", 0.0)) for at, _, p in events]
    )
    events.append((round(end + 0.05, 3), "heal", {}))
    events.sort(key=lambda e: (e[0], e[1]))
    return events


class ChaosClient:
    """Control-plane client: one chaos-free :class:`RpcNode` driving
    every target's ``"Chaos"`` service.  Control frames are exempt
    from the targets' inbound/reply chaos (chaos.py), so this client
    can always reach — and heal — a faulted fleet; a CRASHED target is
    simply unreachable, and calls to it return ``None``."""

    def __init__(self, addrs: Sequence[Addr]) -> None:
        self.node = RpcNode()
        self.sched = self.node.sched
        self.addrs = [tuple(a) for a in addrs]
        self.ends = {a: self.node.client_end(*a) for a in self.addrs}
        self._rng = random.Random(0x0C0A5)

    def call(
        self, addr: Addr, meth: str, args: Any = None,
        timeout: float = 2.0, retries: int = 5,
    ) -> Any:
        for attempt in range(retries):
            reply = self.sched.wait(
                self.ends[addr].call(f"Chaos.{meth}", args), timeout
            )
            if reply is not None and reply is not TIMEOUT:
                return reply
            # Jittered: several ChaosClients retrying against the same
            # recovering target must not re-arrive in lockstep.
            base = 0.05 * (attempt + 1)
            time.sleep(base / 2.0 + self._rng.random() * (base / 2.0))
        return None

    def set_rules(self, addr: Addr, wire: Dict[str, Any]) -> Any:
        return self.call(addr, "set_rules", wire)

    def clear(self, addr: Addr) -> Any:
        return self.call(addr, "clear")

    def clear_all(self) -> None:
        for a in self.addrs:
            self.clear(a)

    def sever(self, addr: Addr, target: Optional[Addr] = None) -> Any:
        return self.call(
            addr, "sever", list(target) if target else None
        )

    def ping(self, addr: Addr) -> bool:
        return self.call(addr, "ping") == "pong"

    def stats(self, addr: Addr) -> Any:
        return self.call(addr, "stats")

    def close(self) -> None:
        self.node.close()


def _rule(**kw) -> Dict[str, Any]:
    return ChaosRule(**kw).to_wire()


def _openloop_surge_fire(
    host: str, port: int, rate: float, dur: float, seed: int,
) -> int:
    """Default ``load_surge`` driver: one open-loop burst from
    benchmarks/openloop.py (imported lazily — the harness package must
    stay importable without the benchmarks tree).  Returns the number
    of requests that got ANY reply (OK, error, or a shed ``ErrBusy``)
    — the window's proof that the burst actually reached the server."""
    from benchmarks.openloop import fire_schedule, gen_schedule

    sched = gen_schedule(seed=seed, rate=rate, duration=dur)
    rep = fire_schedule(host, port, sched, duration=dur, drain_s=1.0)
    return int(rep.get("replied", 0))


class NemesisVerificationError(AssertionError):
    """A scheduled fault window never demonstrably fired — the run was
    a false green (the fleet was never actually under that fault)."""


class Nemesis:
    """Executes a :func:`make_schedule` timeline against live servers.

    ``addrs[i]`` is process ``i``'s ``(host, port)``; ``kill`` /
    ``restart`` are callables taking the process index (the cluster's
    own ``kill``/``start`` methods) and are required only when the
    schedule contains crash events.

    The runner keeps a local model of each target's full rule set and
    pushes complete snapshots on every change — overlapping fault
    windows compose, and a restarted process (which comes back with
    clean rules) is re-pushed its active set.  ``applied`` logs every
    executed action in order, for reproducibility assertions and
    postmortems."""

    def __init__(
        self,
        addrs: Sequence[Addr],
        kill: Optional[Callable[[int], None]] = None,
        restart: Optional[Callable[[int], None]] = None,
        surge_fire: Optional[Callable[..., int]] = None,
        kill_replica: Optional[Callable[[int, int], bool]] = None,
    ) -> None:
        self.addrs = [tuple(a) for a in addrs]
        self.ctl = ChaosClient(self.addrs)
        self._kill = kill
        self._restart = restart
        # kill_replica(gid, peer) -> bool: permanently kill ONE engine
        # replica row (the fleet's kill_replica verb) — required only
        # when the schedule contains kill_replica events.
        self._kill_replica = kill_replica
        # load_surge burst driver: (host, port, rate, dur, seed) ->
        # replied count.  Injectable so fast tests swap in a fake; the
        # default lazy-imports benchmarks/openloop.py (harness modules
        # must not depend on benchmarks at import time).
        self._surge_fire = surge_fire or _openloop_surge_fire
        self._surge_threads: Dict[int, threading.Thread] = {}
        self.applied: List[Tuple[str, str, Dict[str, Any]]] = []
        self._model: Dict[Addr, Dict[str, Any]] = {
            a: {"peers": {}, "all_out": None, "all_in": None, "reply": None}
            for a in self.addrs
        }
        # Window verification ledger (see verify_windows): one record
        # per scheduled fault window, with actual wall times in this
        # process's perf_counter µs domain (so harness/observe.py can
        # overlay them on a merged trace without further alignment).
        self.windows: List[Dict[str, Any]] = []
        self._open: Dict[int, Dict[str, Any]] = {}
        # Procs permanently removed by kill_mesh_process: later windows
        # targeting them are excused instead of pushed into the void.
        self._dead: set = set()
        self.t0_us: Optional[float] = None
        self.error: Optional[BaseException] = None

    # -- model push --------------------------------------------------------

    def _push(self, addr: Addr) -> Optional[Dict[str, Any]]:
        """Push the full rule snapshot; the ack (the target's own
        post-configure snapshot, including its chaos hit ledger) is how
        windows prove they actually landed."""
        return self.ctl.set_rules(addr, self._model[addr])

    def _log(self, phase: str, kind: str, p: Dict[str, Any]) -> None:
        self.applied.append((phase, kind, dict(p)))

    # -- window ledger -----------------------------------------------------

    @staticmethod
    def _hit_count(snap, paths, kinds) -> int:
        hits = (snap or {}).get("hits") or {}
        return sum(
            int((hits.get(path) or {}).get(k, 0))
            for path in paths
            for k in kinds
        )

    def _window(self, kind: str, p: Dict[str, Any], procs) -> Dict[str, Any]:
        w = {
            "kind": kind, "p": dict(p), "procs": list(procs),
            "t_start_us": now_us(), "t_stop_us": None,
            "acked": False, "hits": 0, "baseline": 0, "excused": None,
        }
        self.windows.append(w)
        self._open[id(p)] = w
        return w

    @staticmethod
    def _hit_spec(kind: str, p, addrs) -> List[Tuple[Addr, list, tuple]]:
        """Which (target, hit-ledger paths, fault kinds) prove a window
        of this kind applied at least one fault."""
        if kind == "delay_storm":
            return [(addrs[p["proc"]], ["all_in"], ("delay",))]
        if kind == "drop_storm":
            return [(addrs[p["proc"]], ["all_in", "reply"], ("drop",))]
        if kind == "isolate":
            return [(addrs[p["proc"]], ["all_in"], ("block",))]
        if kind == "partition":
            aa, ab = addrs[p["a"]], addrs[p["b"]]
            return [
                (aa, [f"peer:{ab[0]}:{ab[1]}"], ("block",)),
                (ab, [f"peer:{aa[0]}:{aa[1]}"], ("block",)),
            ]
        if kind == "asym_partition":
            # One-way: only a's outbound edge carries the block rule.
            aa, ab = addrs[p["a"]], addrs[p["b"]]
            return [(aa, [f"peer:{ab[0]}:{ab[1]}"], ("block",))]
        if kind == "partial_partition":
            i = p["proc"]
            a = addrs[i]
            others = p.get("others")
            if others is None:
                others = [x for x in range(len(addrs)) if x != i]
            specs = [(
                a,
                [f"peer:{addrs[x][0]}:{addrs[x][1]}" for x in others],
                ("block",),
            )]
            specs += [
                (addrs[x], [f"peer:{a[0]}:{a[1]}"], ("block",))
                for x in others
            ]
            return specs
        if kind == "slow_link":
            return [(addrs[p["proc"]], ["all_in"], ("floor",))]
        if kind == "fsync_stall":
            return [(addrs[p["proc"]], ["disk"], ("fsync_stall",))]
        return []

    # -- actions -----------------------------------------------------------

    @staticmethod
    def _procs_of(p: Dict[str, Any]) -> List[int]:
        return [p[k] for k in ("proc", "a", "b") if k in p]

    def _start(self, kind: str, p: Dict[str, Any]) -> None:
        self._log("start", kind, p)
        procs = self._procs_of(p)
        if (
            kind not in ("heal", "kill_mesh_process")
            and any(x in self._dead for x in procs)
        ):
            # Target already permanently killed — nothing to fault.
            w = self._window(kind, p, procs)
            w["acked"] = True
            w["excused"] = "target killed (kill_mesh_process)"
            w["t_stop_us"] = now_us()
            self._open.pop(id(p), None)
            return
        if kind == "delay_storm":
            a = self.addrs[p["proc"]]
            w = self._window(kind, p, [p["proc"]])
            self._model[a]["all_in"] = _rule(
                delay=p["prob"], delay_min=p["delay_min"],
                delay_max=p["delay_max"],
            )
            self._ack_start(w, [self._push(a)])
        elif kind == "drop_storm":
            a = self.addrs[p["proc"]]
            w = self._window(kind, p, [p["proc"]])
            self._model[a]["all_in"] = _rule(drop=p["prob"])
            # Reply drops: the op APPLIED but the ack is lost — only
            # session dedup keeps the client's retry exactly-once.
            self._model[a]["reply"] = _rule(drop=p["prob"] / 2.0)
            self._ack_start(w, [self._push(a)])
        elif kind == "isolate":
            a = self.addrs[p["proc"]]
            w = self._window(kind, p, [p["proc"]])
            self._model[a]["all_in"] = _rule(block=True)
            self._ack_start(w, [self._push(a)])
        elif kind == "partition":
            aa, ab = self.addrs[p["a"]], self.addrs[p["b"]]
            w = self._window(kind, p, [p["a"], p["b"]])
            self._model[aa]["peers"][f"{ab[0]}:{ab[1]}"] = _rule(block=True)
            self._model[ab]["peers"][f"{aa[0]}:{aa[1]}"] = _rule(block=True)
            self._ack_start(w, [self._push(aa), self._push(ab)])
        elif kind == "asym_partition":
            # ONE-WAY block: a's frames toward b vanish; b→a flows.
            # Only a carries a rule — the fault class check-quorum must
            # catch (the leader's appends die while everything it hears
            # says the fleet is healthy).
            aa, ab = self.addrs[p["a"]], self.addrs[p["b"]]
            w = self._window(kind, p, [p["a"], p["b"]])
            self._model[aa]["peers"][f"{ab[0]}:{ab[1]}"] = _rule(block=True)
            self._ack_start(w, [self._push(aa)])
        elif kind == "partial_partition":
            # Sever proc i from every OTHER engine process, both
            # directions, via per-peer rules only: client connections
            # match no peer rule, so a leader living on i still hears
            # its clerks while its quorum is gone — the wedge-shaped
            # gray failure the check-quorum stepdown exists for.
            i = p["proc"]
            a = self.addrs[i]
            others = [
                x for x in range(len(self.addrs))
                if x != i and x not in self._dead
            ]
            p["others"] = others  # pinned for _stop/_hit_spec symmetry
            w = self._window(kind, p, [i] + others)
            for x in others:
                b = self.addrs[x]
                self._model[a]["peers"][f"{b[0]}:{b[1]}"] = _rule(block=True)
                self._model[b]["peers"][f"{a[0]}:{a[1]}"] = _rule(block=True)
            self._ack_start(
                w,
                [self._push(a)] + [self._push(self.addrs[x]) for x in others],
            )
        elif kind == "slow_link":
            a = self.addrs[p["proc"]]
            w = self._window(kind, p, [p["proc"]])
            # Latency floor on EVERY inbound frame — degraded-but-alive,
            # where delay_storm is probabilistic burst jitter.
            self._model[a]["all_in"] = _rule(floor=p["floor"])
            self._ack_start(w, [self._push(a)])
        elif kind == "fsync_stall":
            a = self.addrs[p["proc"]]
            w = self._window(kind, p, [p["proc"]])
            ack = self.ctl.call(a, "fsync_stall", [p["stall"]])
            w["acked"] = ack is not None
            # Stall hits land in the target's chaos ledger ("disk"
            # path) as storage traffic syncs; baseline from stats, not
            # from a rule push (the stall is not a wire rule).
            w["baseline"] = self._hit_count(
                self.ctl.stats(a), ["disk"], ("fsync_stall",)
            )
            if not w["acked"]:
                w["excused"] = "fsync_stall push unacknowledged (target down?)"
        elif kind == "load_surge":
            a = self.addrs[p["proc"]]
            w = self._window(kind, p, [p["proc"]])
            w["acked"] = True  # the burst thread is ours to run
            seed = int(p["rate"]) + 1009 * p["proc"]

            def _burst(w=w, a=a, p=p, seed=seed) -> None:
                try:
                    w["hits"] = int(self._surge_fire(
                        a[0], a[1], p["rate"], p["dur"], seed,
                    ))
                except Exception as exc:  # noqa: BLE001 - ledgered
                    w["acked"] = False
                    w["excused"] = f"surge burst failed: {exc!r}"

            t = threading.Thread(
                target=_burst, name="nemesis-surge", daemon=True,
            )
            self._surge_threads[id(p)] = t
            t.start()
        elif kind == "sever":
            w = self._window(kind, p, [p["proc"]])
            cut = self.ctl.sever(self.addrs[p["proc"]])
            w["acked"] = cut is not None
            w["hits"] = int(cut or 0)
            w["t_stop_us"] = now_us()
            self._open.pop(id(p), None)
        elif kind == "crash":
            if self._kill is None:
                raise ValueError("crash event but no kill callback")
            w = self._window(kind, p, [p["proc"]])
            self._kill(p["proc"])
            w["acked"] = True  # the kill callback ran
        elif kind == "kill_mesh_process":
            # Permanent: no paired stop, no restart.  Recovery is the
            # placement controller's job, not the nemesis's.
            if self._kill is None:
                raise ValueError(
                    "kill_mesh_process event but no kill callback"
                )
            w = self._window(kind, p, [p["proc"]])
            self._kill(p["proc"])
            self._dead.add(p["proc"])
            w["acked"] = True
            w["t_stop_us"] = now_us()
            self._open.pop(id(p), None)
        elif kind == "kill_replica":
            # Permanent single-replica death (the process lives):
            # healing is the placement controller's joint-consensus
            # replacement, never a restart.
            if self._kill_replica is None:
                raise ValueError(
                    "kill_replica event but no kill_replica callback"
                )
            w = self._window(kind, p, [])
            w["acked"] = bool(
                self._kill_replica(p["gid"], p["peer"])
            )
            if not w["acked"]:
                w["excused"] = "replica not hosted (already moved?)"
            w["t_stop_us"] = now_us()
            self._open.pop(id(p), None)
        elif kind == "heal":
            self.heal_all()
        else:
            raise ValueError(f"unknown nemesis action {kind!r}")

    def _ack_start(self, w: Dict[str, Any], acks) -> None:
        w["acked"] = all(a is not None for a in acks)
        spec = self._hit_spec(w["kind"], w["p"], self.addrs)
        w["baseline"] = sum(
            self._hit_count(ack, paths, kinds)
            for ack, (_, paths, kinds) in zip(acks, spec)
        )
        if not w["acked"]:
            # The only reachable-in-theory failure: the target is down
            # (an overlapping crash window) — the control plane itself
            # is chaos-exempt, so a live target always acks.
            w["excused"] = "start push unacknowledged (target down?)"

    def _stop(self, kind: str, p: Dict[str, Any]) -> None:
        self._log("stop", kind, p)
        w = self._open.pop(id(p), None)
        if any(x in self._dead for x in self._procs_of(p)):
            # The window's target died permanently mid-window; there is
            # no rule state left to tear down.
            if w is not None:
                w["t_stop_us"] = now_us()
                w["excused"] = (
                    w["excused"] or "target killed (kill_mesh_process)"
                )
            return
        if kind == "load_surge":
            # The burst fires for exactly p["dur"]; the stop action
            # lands right as it ends, so the join is a drain wait.
            t = self._surge_threads.pop(id(p), None)
            if t is not None:
                t.join(timeout=p["dur"] + 15.0)
            if w is not None:
                w["t_stop_us"] = now_us()
                if t is not None and t.is_alive():
                    w["acked"] = False
                    w["excused"] = "surge burst never finished"
        elif kind in ("delay_storm", "drop_storm", "isolate", "partition",
                      "asym_partition", "partial_partition", "slow_link",
                      "fsync_stall"):
            if kind == "partition":
                aa, ab = self.addrs[p["a"]], self.addrs[p["b"]]
                self._model[aa]["peers"].pop(f"{ab[0]}:{ab[1]}", None)
                self._model[ab]["peers"].pop(f"{aa[0]}:{aa[1]}", None)
                acks = [self._push(aa), self._push(ab)]
            elif kind == "asym_partition":
                aa, ab = self.addrs[p["a"]], self.addrs[p["b"]]
                self._model[aa]["peers"].pop(f"{ab[0]}:{ab[1]}", None)
                acks = [self._push(aa)]
            elif kind == "partial_partition":
                i = p["proc"]
                a = self.addrs[i]
                others = [
                    x for x in p.get("others", ())
                    if x not in self._dead
                ]
                for x in others:
                    b = self.addrs[x]
                    self._model[a]["peers"].pop(f"{b[0]}:{b[1]}", None)
                    self._model[b]["peers"].pop(f"{a[0]}:{a[1]}", None)
                acks = [self._push(a)] + [
                    self._push(self.addrs[x]) for x in others
                ]
            elif kind == "fsync_stall":
                a = self.addrs[p["proc"]]
                # Lift the stall, then read the hit delta from stats
                # (the stall is armed by verb, not by a wire rule).
                lifted = self.ctl.call(a, "fsync_stall", [0.0])
                acks = [
                    self.ctl.stats(a) if lifted is not None else None
                ]
            else:
                a = self.addrs[p["proc"]]
                self._model[a]["all_in"] = None
                if kind == "drop_storm":
                    self._model[a]["reply"] = None
                acks = [self._push(a)]
            if w is not None:
                w["t_stop_us"] = now_us()
                spec = self._hit_spec(kind, p, self.addrs)
                if all(a is not None for a in acks):
                    total = sum(
                        self._hit_count(ack, paths, kinds)
                        for ack, (_, paths, kinds) in zip(acks, spec)
                    )
                    w["hits"] = max(0, total - w["baseline"])
                else:
                    w["excused"] = (
                        w["excused"] or "stop push unacknowledged"
                    )
        elif kind == "crash":
            if self._restart is None:
                raise ValueError("crash event but no restart callback")
            self._restart(p["proc"])
            # The reborn process has clean rules; re-push its active
            # set so a crash inside another fault window composes.
            ack = self._push(self.addrs[p["proc"]])
            if w is not None:
                w["t_stop_us"] = now_us()
            if ack is not None:
                # Open windows targeting this proc had their rules
                # re-installed by that push — they are live after all.
                for w2 in self.windows:
                    if (
                        w2["t_stop_us"] is None
                        and p["proc"] in w2["procs"]
                        and not w2["acked"]
                    ):
                        w2["acked"] = True
                        w2["excused"] = "re-acked after crash restart"

    def heal_all(self) -> None:
        for a in self.addrs:
            self._model[a] = {
                "peers": {}, "all_out": None, "all_in": None, "reply": None,
            }
        self.ctl.clear_all()

    # -- verification ------------------------------------------------------

    def verify_windows(self, require_hits: Sequence[str] = ()) -> None:
        """Assert every scheduled fault window demonstrably fired.

        Baseline check (always): each window's rule push was
        acknowledged by the target (the control plane is chaos-exempt,
        so an unacked push means the window silently missed), each
        crash's kill callback ran, each sever got a cut-count reply.
        A window whose target was down for an overlapping crash is
        excused only if the restart re-push re-installed its rules.

        ``require_hits`` names window kinds (e.g. ``("drop_storm",)``)
        that must additionally show ≥ 1 fault actually applied (chaos
        hit-ledger delta over the window) — stricter, but only sound
        when the caller guarantees traffic at the faulted process
        during every window.  Raises :class:`NemesisVerificationError`
        listing every silent miss."""
        bad: List[str] = []
        for n, w in enumerate(self.windows):
            tag = f"window {n}: {w['kind']} {w['p']}"
            if not w["acked"]:
                bad.append(f"{tag} — never acknowledged"
                           f" ({w['excused'] or 'no excuse recorded'})")
            elif (
                w["kind"] in require_hits and w["hits"] < 1
                and not w["excused"]
            ):
                bad.append(f"{tag} — acked but zero faults applied")
        if bad:
            reason = (
                "scheduled fault windows did not fire:\n  "
                + "\n  ".join(bad)
            )
            self._auto_bundle(reason)
            raise NemesisVerificationError(reason)

    def _auto_bundle(self, reason: str) -> Optional[str]:
        """Collect a postmortem bundle when ``MRT_POSTMORTEM_DIR`` is
        set (timestamped subdirectory).  Verification failures are
        exactly the runs worth a black-box readout, and by the time a
        human looks, the fleet is gone — so collection is automatic
        and best-effort (never masks the verification error)."""
        root = knob_str("MRT_POSTMORTEM_DIR")
        if not root:
            return None
        from .bundle import collect_bundle  # local: avoid import cycle

        out = os.path.join(
            root, f"nemesis-{os.getpid()}-{int(time.time() * 1000)}"
        )
        try:
            return collect_bundle(
                out, addrs=self.addrs, reason=reason,
                windows=self.windows, t0_us=self.t0_us,
            )
        except Exception:  # pragma: no cover - best-effort by design
            return None

    # -- execution ---------------------------------------------------------

    def run(self, schedule: Sequence[Event], verify: bool = True) -> None:
        """Execute the timeline in this thread.  Blocking actions
        (restart-from-WAL waits for the readiness line) push later
        actions back; the log records intent order, which is the
        deterministic part.  With ``verify`` (default), raises
        :class:`NemesisVerificationError` at the end if any window
        silently missed (see :meth:`verify_windows`)."""
        actions: List[Tuple[float, int, str, str, Dict[str, Any]]] = []
        for n, (at, kind, p) in enumerate(schedule):
            if kind in ("delay_storm", "drop_storm", "isolate",
                        "partition", "asym_partition", "partial_partition",
                        "slow_link", "fsync_stall", "load_surge"):
                actions.append((at, n, "start", kind, p))
                actions.append((at + p["dur"], n, "stop", kind, p))
            elif kind == "crash":
                actions.append((at, n, "start", kind, p))
                actions.append((at + p["down"], n, "stop", kind, p))
            else:  # sever / heal: instantaneous
                actions.append((at, n, "start", kind, p))
        actions.sort(key=lambda a: (a[0], a[1], a[2] == "start"))
        t0 = time.monotonic()
        # Anchor for timeline overlays: schedule second ``at`` maps to
        # perf_counter µs ``self.t0_us + at*1e6`` in this process.
        self.t0_us = now_us()
        for at, _, phase, kind, p in actions:
            delay = at - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            if phase == "start":
                self._start(kind, p)
            else:
                self._stop(kind, p)
        if verify:
            self.verify_windows()

    def run_async(self, schedule: Sequence[Event]) -> threading.Thread:
        """Run the schedule on a daemon thread (the usual shape: the
        nemesis runs WHILE the caller applies clerk load).  Join the
        returned thread, then call :meth:`verify_windows` — a raise
        inside the daemon thread would vanish, so auto-verify is off
        here and any execution error is re-raised from ``self.error``
        by :meth:`verify_windows`'s caller checking it (or just read
        ``nem.error`` after join)."""
        self.error: Optional[BaseException] = None

        def _run() -> None:
            try:
                self.run(list(schedule), verify=False)
            except BaseException as exc:  # noqa: BLE001 - surfaced via .error
                self.error = exc

        t = threading.Thread(target=_run, name="nemesis", daemon=True)
        t.start()
        return t

    def close(self) -> None:
        self.ctl.close()


def run_clerk_load(
    make_clerk: Callable[[], Any],
    keys: Sequence[str],
    n_workers: int = 3,
    ops_per_worker: int = 9,
    op_timeout: float = 90.0,
    trace_sink: Optional[list] = None,
) -> list:
    """Concurrent blocking-clerk load returning a porcupine history.

    Each worker owns one clerk and alternates appends (unique
    ``(worker.op)`` tags — exactly-once is checkable afterwards from
    any Get) with gets.  ``op_timeout`` must exceed the schedule's
    longest fault window: every fault heals itself, so a retrying
    clerk always converges and the history contains no ambiguous
    (timed-out) operations — porcupine then checks completed ops only.

    ``trace_sink``: a list that collects each clerk node's trace
    events (drained just before the clerk closes — clerk-side request
    spans would otherwise die with the node).  Events are already in
    this process's clock domain; harness/observe.py merges them with
    the servers' scraped traces.

    Worker exceptions propagate after all threads join (a hung clerk
    is a test failure, not a deadlock)."""
    from ..porcupine.kv import OP_APPEND, OP_GET, KvInput, KvOutput
    from ..porcupine.model import Operation

    history: list = []
    lock = threading.Lock()
    failures: list = []

    def worker(wid: int) -> None:
        ck = make_clerk()
        try:
            for j in range(ops_per_worker):
                key = keys[(wid + j) % len(keys)]
                t0 = time.monotonic()
                if j % 3 == 2:
                    v = ck.get(key, timeout=op_timeout)
                    inp = KvInput(op=OP_GET, key=key)
                    out = KvOutput(value=v)
                else:
                    tag = f"({wid}.{j})"
                    ck.append(key, tag, timeout=op_timeout)
                    inp = KvInput(op=OP_APPEND, key=key, value=tag)
                    out = KvOutput(value="")
                with lock:
                    history.append(Operation(
                        client_id=ck.client_id, input=inp, call=t0,
                        output=out, ret=time.monotonic(),
                    ))
        except Exception as exc:  # noqa: BLE001 - reported after join
            failures.append((wid, exc))
        finally:
            if trace_sink is not None:
                node = getattr(ck, "node", None)
                obs = getattr(node, "obs", None)
                if obs is not None:
                    events, _dropped = obs.tracer.drain()
                    with lock:
                        trace_sink.extend(events)
            ck.close()

    threads = [
        threading.Thread(target=worker, args=(i,), name=f"clerk-{i}")
        for i in range(n_workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if failures:
        raise failures[0][1]
    return history
