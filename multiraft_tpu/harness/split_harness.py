"""In-process drive harness for SPLIT deployments (engine/split.py,
engine/split_shard.py): several 'processes' (drivers + services +
peerings) in one interpreter with a deterministic manual slab shuttle —
the same extract/inject machinery the socket servers run, minus the
sockets.  Shared by tests/test_engine_split_shard.py and
``__graft_entry__._dryrun_split_shard`` so the two cannot drift (the
retry/dedup discipline lives here exactly once).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

__all__ = ["SplitShardRig"]


class SplitShardRig:
    """Drives a set of :class:`~multiraft_tpu.engine.split_shard.
    SplitShardKV` sides.  ``sides`` is a list of ``(service, peering)``
    pairs built with the SAME owners map; ``alive[i] = False`` models a
    kill -9 of process ``i`` (its pump stops, its slabs stop flowing —
    exactly what the socket form loses)."""

    # Stable admin identity: retries of one logical admin op may land
    # at DIFFERENT sides across failovers; a fixed (client, command)
    # pair dedups them exactly-once through the replicated ctrler log.
    ADMIN_CLIENT = 424242
    CLIENT = 777

    def __init__(self, sides: Sequence[Tuple[Any, Any]]) -> None:
        self.sides = list(sides)
        self.alive = [True] * len(self.sides)
        self._cmd = 0
        self._admin_cmd = 0

    # -- the shuttle -------------------------------------------------------

    def shuttle(self, rounds: int = 1) -> None:
        """One round = each live side pumps one tick, then its boundary
        slabs are delivered to the other live sides (dead sides neither
        pump nor receive)."""
        for _ in range(rounds):
            for i, (svc, peering) in enumerate(self.sides):
                if not self.alive[i]:
                    continue
                svc.pump(1)
                for proc, slab in peering.extract().items():
                    if self.alive[proc]:
                        self.sides[proc][1].inject(slab)

    def kill(self, i: int) -> None:
        self.alive[i] = False

    # -- election settling -------------------------------------------------

    def settle(self, G: int, max_rounds: int = 600) -> None:
        """Shuttle until every engine group has exactly one leader
        across the live sides."""
        for _ in range(max_rounds):
            self.shuttle()
            per_side = [
                s[0].driver.leaders_per_group()
                for i, s in enumerate(self.sides)
                if self.alive[i]
            ]
            if all(
                sum(int(a[g]) for a in per_side) == 1 for g in range(G)
            ):
                return
        raise TimeoutError("split groups did not elect a single leader")

    # -- admin / client drive ---------------------------------------------

    def admin(self, kind: str, arg: Any, max_rounds: int = 2000) -> None:
        """Drive a ctrler op at whichever live side owns the ctrler
        leader, retrying under ONE (client, command) identity across
        failovers — so a retry that lands at a different side dedups
        against a commit the caller never saw acked.  The command id
        comes from the RIG's counter and is always passed explicitly:
        letting the accepting side auto-allocate would collide two
        successive admin ops accepted by different sides (each side's
        local counter starts at 0) and the second would be silently
        dedup-swallowed as a duplicate."""
        self._admin_cmd += 1
        cid = self._admin_cmd
        t = None
        for _ in range(max_rounds):
            if t is not None and t.done and not t.failed:
                return
            if t is None or t.done:
                for i, (svc, _) in enumerate(self.sides):
                    if self.alive[i]:
                        nt = svc.ctrl_local(
                            kind, arg, command_id=cid,
                            client_id=self.ADMIN_CLIENT,
                        )
                        if nt is not None:
                            t = nt
                            break
            self.shuttle()
        raise TimeoutError(f"ctrler {kind} never committed")

    def client_op(self, op: str, key: str, value: str = "",
                  max_rounds: int = 2000) -> str:
        """The reference clerk loop across sides: route by the latest
        config, submit at the owning group's leader side, retry on
        wrong-group/lost-leader under one (client, command) so
        resubmits stay exactly-once."""
        from ..services.shardkv import key2shard

        self._cmd += 1
        cid = self._cmd
        t = None
        for _ in range(max_rounds):
            if t is not None and t.done and not t.failed and t.err == "OK":
                return t.value
            if t is None or t.done:
                t = None
                live = [s for i, s in enumerate(self.sides) if self.alive[i]]
                if live:
                    cfg = live[0][0].query_latest()
                    gid = cfg.shards[key2shard(key)]
                    for svc, _ in live:
                        if gid in svc.reps:
                            nt = svc.submit_local(
                                gid, op, key, value,
                                client_id=self.CLIENT, command_id=cid,
                            )
                            if nt is not None:
                                t = nt
                                break
            self.shuttle()
        raise TimeoutError(f"{op}({key!r}) never committed")

    # -- migration observation --------------------------------------------

    def migrating(self) -> bool:
        """Any live side observes any non-SERVING shard slot."""
        from ..services.shardkv import SERVING

        return any(
            sl.state != SERVING
            for i, (svc, _) in enumerate(self.sides) if self.alive[i]
            for rep in svc.reps.values()
            for sl in rep.shards.values()
        )

    def wait_migrating(self, max_rounds: int = 1500) -> bool:
        for _ in range(max_rounds):
            self.shuttle()
            if self.migrating():
                return True
        return False

    def wait_migrated(self, gids: Sequence[int],
                      max_rounds: int = 4000) -> None:
        """Shuttle until every live side's replicas are SERVING-stable
        at the latest config (migration + Challenge-1 GC complete)."""
        from ..services.shardkv import SERVING

        for _ in range(max_rounds):
            self.shuttle()
            live = [s for i, s in enumerate(self.sides) if self.alive[i]]
            latest = max(s[0].configs[-1].num for s in live)
            if all(
                svc.reps[gid].cur.num == latest
                and all(
                    sl.state == SERVING
                    for sl in svc.reps[gid].shards.values()
                )
                for svc, _ in live
                for gid in gids
            ):
                return
        raise TimeoutError("migration never completed")
