"""Postmortem bundle collection: freeze the fleet's black boxes.

When a run goes wrong — a nemesis window silently misses, a chaos test
fails, a process dies when it shouldn't — the evidence is scattered
across N processes, some of them already dead.  :func:`collect_bundle`
gathers everything a postmortem needs into ONE directory while it is
still fresh:

* ``manifest.json``  — addresses, per-address clock offsets (min-RTT
  estimates, cached so a DEAD process keeps the offset measured while
  it lived), pid/name idents, unreachable list, collection reason.
* ``snapshots.json`` — final ``Obs.snapshot`` per process, with
  explicit ``{"missing": true}`` markers for the dead
  (:meth:`FleetObserver.snapshot_all`).
* ``tails.json``     — per-process tail exemplars (``Obs.tail``,
  peeked non-destructively), best-effort: the slowest requests each
  survivor was holding, with full stage/wait vectors.
* ``rings/``         — every ``flight-<pid>.ring`` from the flight
  recorder directory, copied byte-for-byte.  The rings are the only
  evidence that survives SIGKILL; copying them into the bundle pins
  the run's state before a retry or cleanup overwrites it.
* ``trace.json.gz``  — the merged clock-aligned fleet timeline
  (best-effort: reachable processes only, missing rows marked).
* ``windows.json``   — the nemesis fault-window ledger, when given.

The bundle is self-contained: ``python -m
multiraft_tpu.analysis.postmortem <bundle>`` needs nothing else.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..distributed import flightrec
from ..distributed.observe import now_us
from ..utils.knobs import knob_str
from .observe import FleetObserver

__all__ = ["collect_bundle"]

Addr = Tuple[str, int]


def _jsonable(obj: Any) -> Any:
    """Best-effort plain-data projection (windows ledgers hold only
    plain types today; ``default=str`` guards future additions)."""
    return json.loads(json.dumps(obj, default=str))


def collect_bundle(
    out_dir: str,
    addrs: Sequence[Addr] = (),
    observer: Optional[FleetObserver] = None,
    reason: str = "",
    windows: Sequence[Dict[str, Any]] = (),
    schedule: Sequence[Any] = (),
    t0_us: Optional[float] = None,
    local_events: Sequence[Dict[str, Any]] = (),
    flight_dir: Optional[str] = None,
) -> str:
    """Collect a postmortem bundle into ``out_dir`` and return it.

    Pass an existing ``observer`` to reuse its cached clock offsets and
    pid idents (essential: a process that died mid-run can only be
    clock-aligned from offsets measured before death); otherwise a
    throwaway :class:`FleetObserver` over ``addrs`` is created and
    closed.  Never raises on a partially dead fleet — collecting less
    evidence beats collecting none."""
    owned = observer is None
    if observer is None:
        observer = FleetObserver(list(addrs))
    try:
        os.makedirs(out_dir, exist_ok=True)

        # Flush this host process's own ring so clerk/nemesis records
        # written microseconds ago are on disk before the copy.
        rec = flightrec.get_recorder()
        if rec is not None:
            rec.flush()

        snaps = observer.snapshot_all()
        with open(os.path.join(out_dir, "snapshots.json"), "w") as f:
            json.dump(snaps, f, indent=2, sort_keys=True, default=str)

        try:
            # Tail exemplars, NON-destructively (reset=False): evidence
            # collection must not consume the window a concurrent
            # loadcurve scrape is about to drain.  Best-effort — a
            # fleet with MRT_TAIL=0 just reports tail: null rows.
            tails = observer.tail_all(reset=False)
            if any(
                isinstance(t, dict) and t.get("tail") is not None
                for t in tails.values()
            ):
                with open(os.path.join(out_dir, "tails.json"), "w") as f:
                    json.dump(
                        tails, f, indent=2, sort_keys=True, default=str
                    )
        except Exception:
            pass  # same contract as the timeline: rings are load-bearing

        try:
            tr = observer.merged_timeline(
                local_events=local_events, windows=windows,
                schedule=schedule, t0_us=t0_us,
            )
            tr.save(os.path.join(out_dir, "trace.json.gz"))
        except Exception:
            pass  # the rings + snapshots are the load-bearing evidence

        if windows:
            with open(os.path.join(out_dir, "windows.json"), "w") as f:
                json.dump(_jsonable(list(windows)), f, indent=2)

        fdir = flight_dir or knob_str("MRT_FLIGHTREC_DIR")
        rings: List[str] = []
        if fdir and os.path.isdir(fdir):
            rdir = os.path.join(out_dir, "rings")
            os.makedirs(rdir, exist_ok=True)
            for p in sorted(glob.glob(os.path.join(fdir, "flight-*.ring"))):
                try:
                    shutil.copy2(p, rdir)
                    rings.append(os.path.basename(p))
                except OSError:
                    continue

        manifest = {
            "reason": reason,
            "created_at": time.time(),
            "host_now_us": now_us(),
            "host_pid": os.getpid(),
            "addrs": [f"{h}:{p}" for h, p in observer.addrs],
            "offsets_us": {
                f"{h}:{p}": off
                for (h, p), off in observer.offsets.items()
            },
            "idents": {
                f"{h}:{p}": {"pid": pid, "name": name}
                for (h, p), (pid, name) in observer.idents.items()
            },
            "unreachable": [f"{h}:{p}" for h, p in observer.unreachable],
            "rings": rings,
            "flight_dir": fdir,
        }
        with open(os.path.join(out_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
        return out_dir
    finally:
        if owned:
            observer.close()
