"""shardkv test fixture (reference: shardkv/config.go:204-382).

One network hosting a 3-server controller cluster plus ``ngroups`` KV
group clusters; ``join``/``leave`` drive real controller clerk ops
(reference: shardkv/config.go:306-334); groups can be shut down and
restarted wholesale with persisted state."""

from __future__ import annotations

import random
from typing import Any, Dict, List

from ..raft.persister import Persister
from ..services.shardctrler import CtrlerClerk, ShardCtrler
from ..services.shardkv import ShardClerk, ShardKVServer
from ..sim.scheduler import Scheduler
from ..transport.network import ClientEnd, Network
from .cluster import Cluster

__all__ = ["ShardKVHarness"]


class ShardKVHarness:
    def __init__(
        self,
        n: int = 3,
        ngroups: int = 3,
        unreliable: bool = False,
        maxraftstate: int = -1,
        seed: int = 0,
    ) -> None:
        self.sched = Scheduler()
        self.net = Network(self.sched, seed=seed)
        self.net.set_reliable(not unreliable)
        self.n = n
        self.ngroups = ngroups
        self.maxraftstate = maxraftstate
        self.rng = random.Random(seed ^ 0x5A4D)
        self.seed = seed
        self._end_counter = 0

        def ctrler_factory(ends, i, persister: Persister, srv_seed: int):
            srv = ShardCtrler(self.sched, ends, i, persister, seed=srv_seed)
            return srv, {"ShardCtrler": srv, "Raft": srv.rf}

        self.ctl = Cluster(
            self.sched, self.net, "ctl", 3, ctrler_factory, self.rng, seed=seed
        )
        self.ctl.start_all()

        self.gids = [100 + k for k in range(ngroups)]
        self.groups: Dict[int, Cluster] = {}
        for gid in self.gids:
            self.groups[gid] = self._make_group(gid)
            self.groups[gid].start_all()

        self.ctl_ck = CtrlerClerk(self.sched, self._ctrler_ends())

    # -- plumbing ---------------------------------------------------------

    def make_end(self, servername: Any) -> ClientEnd:
        """Fresh uniquely-named endpoint to any server
        (reference: shardkv/config.go make_end closure)."""
        self._end_counter += 1
        name = ("dyn", self._end_counter, servername)
        end = self.net.make_end(name)
        self.net.connect(name, servername)
        self.net.enable(name, True)
        return end

    def _ctrler_ends(self) -> List[ClientEnd]:
        return [self.make_end(self.ctl.server_name(j)) for j in range(3)]

    def _make_group(self, gid: int) -> Cluster:
        def factory(ends, i, persister: Persister, srv_seed: int):
            srv = ShardKVServer(
                self.sched,
                ends,
                i,
                persister,
                gid=gid,
                ctrler_ends=self._ctrler_ends(),
                make_end=self.make_end,
                maxraftstate=self.maxraftstate,
                seed=srv_seed,
            )
            return srv, {"ShardKV": srv, "Raft": srv.rf}

        return Cluster(
            self.sched,
            self.net,
            ("skv", gid),
            self.n,
            factory,
            self.rng,
            seed=self.seed + gid,
        )

    def group_servers(self, gid: int) -> List[Any]:
        return [self.groups[gid].server_name(i) for i in range(self.n)]

    # -- membership (reference: shardkv/config.go:306-334) ----------------

    def join(self, gid: int) -> None:
        self.run(self.ctl_ck.join({gid: self.group_servers(gid)}))

    def joinm(self, gids: List[int]) -> None:
        servers = {gid: self.group_servers(gid) for gid in gids}
        self.run(self.ctl_ck.join(servers))

    def leave(self, gid: int) -> None:
        self.run(self.ctl_ck.leave([gid]))

    def leavem(self, gids: List[int]) -> None:
        self.run(self.ctl_ck.leave(list(gids)))

    # -- group lifecycle --------------------------------------------------

    def shutdown_group(self, gid: int) -> None:
        for i in range(self.n):
            self.groups[gid].shutdown_server(i)

    def start_group(self, gid: int) -> None:
        for i in range(self.n):
            self.groups[gid].start_server(i)
        self.groups[gid].connect_all()

    # -- clients ----------------------------------------------------------

    def make_client(self) -> ShardClerk:
        return ShardClerk(self.sched, self._ctrler_ends(), self.make_end)

    # -- stats ------------------------------------------------------------

    def total_group_storage(self) -> int:
        """Raft state + snapshot bytes across all group replicas
        (Challenge 1 gate, reference: shardkv/test_test.go:794-810)."""
        total = 0
        for gid in self.gids:
            for p in self.groups[gid].saved:
                total += p.raft_state_size() + p.snapshot_size()
        return total

    def run(self, gen):
        return self.sched.run_until(self.sched.spawn(gen))

    def cleanup(self) -> None:
        for c in self.groups.values():
            c.kill_all()
        self.ctl.kill_all()
        self.net.cleanup()
