"""shardctrler test fixture (reference: shardctrler/config.go)."""

from __future__ import annotations

import random

from ..raft.persister import Persister
from ..services.shardctrler import CtrlerClerk, ShardCtrler
from ..sim.scheduler import Scheduler
from ..transport.network import Network
from .cluster import Cluster

__all__ = ["CtrlerHarness"]


class CtrlerHarness:
    def __init__(self, n: int, unreliable: bool = False, seed: int = 0) -> None:
        self.sched = Scheduler()
        self.net = Network(self.sched, seed=seed)
        self.net.set_reliable(not unreliable)
        self.n = n
        self.rng = random.Random(seed ^ 0xC71E)

        def factory(ends, i, persister: Persister, srv_seed: int):
            srv = ShardCtrler(self.sched, ends, i, persister, seed=srv_seed)
            return srv, {"ShardCtrler": srv, "Raft": srv.rf}

        self.cluster = Cluster(
            self.sched, self.net, "ctl", n, factory, self.rng, seed=seed
        )
        self.cluster.start_all()

    @property
    def servers(self):
        return self.cluster.handles

    def make_client(self) -> CtrlerClerk:
        return CtrlerClerk(self.sched, self.cluster.make_client_ends())

    def run(self, gen):
        return self.sched.run_until(self.sched.spawn(gen))

    def cleanup(self) -> None:
        self.cluster.kill_all()
        self.net.cleanup()
