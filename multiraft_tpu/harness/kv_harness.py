"""kvraft test fixture (reference: kvraft/config.go).

Same incarnation-fresh endpoint discipline as the Raft harness, plus:
clerk factories with per-clerk endpoints and shuffled server order
(reference: kvraft/config.go:194-212,37-45), a 2-way server partitioner
(reference: kvraft/config.go:177-189; clerks stay connected to all
servers — their RPCs into a minority side simply fail to commit), and
crash/restart that preserves persisted state
(reference: kvraft/config.go:258-326).
"""

from __future__ import annotations

import random
from typing import List, Optional

from ..raft.persister import Persister
from ..services.kvraft import Clerk, KVServer
from ..sim.scheduler import Scheduler
from ..transport.network import Network, Server, Service

__all__ = ["KVHarness"]


class KVHarness:
    def __init__(
        self,
        n: int,
        unreliable: bool = False,
        maxraftstate: int = -1,
        seed: int = 0,
    ) -> None:
        self.sched = Scheduler()
        self.net = Network(self.sched, seed=seed)
        self.net.set_reliable(not unreliable)
        self.n = n
        self.seed = seed
        self.rng = random.Random(seed ^ 0xBEEF)
        self.maxraftstate = maxraftstate
        self.servers: List[Optional[KVServer]] = [None] * n
        self.saved: List[Persister] = [Persister() for _ in range(n)]
        self.endnames: List[List[object]] = [[None] * n for _ in range(n)]
        self.groups = [0] * n  # current partition side per server
        self._incarnation = 0
        self._next_clerk = 0
        self.clerks: dict = {}  # clerk -> list of its endnames
        for i in range(n):
            self.start_server(i)
        self.connect_all()

    # -- server lifecycle ------------------------------------------------

    def start_server(self, i: int) -> None:
        """(reference: kvraft/config.go StartServer:283-326)"""
        if self.servers[i] is not None:
            self.shutdown_server(i)
        self._incarnation += 1
        inc = self._incarnation
        ends = []
        for j in range(self.n):
            name = ("kv", i, j, inc)
            self.endnames[i][j] = name
            end = self.net.make_end(name)
            self.net.connect(name, j)
            ends.append(end)
        persister = self.saved[i].copy()
        self.saved[i] = persister
        srv_obj = KVServer(
            self.sched,
            ends,
            i,
            persister,
            maxraftstate=self.maxraftstate,
            seed=self.seed * 977 + inc,
        )
        self.servers[i] = srv_obj
        server = Server()
        server.add_service(Service(srv_obj, name="KVServer"))
        server.add_service(Service(srv_obj.rf, name="Raft"))
        self.net.add_server(i, server)
        self._apply_edges()

    def shutdown_server(self, i: int) -> None:
        """(reference: kvraft/config.go ShutdownServer:258-281)"""
        self.net.delete_server(i)
        self.saved[i] = self.saved[i].copy()
        if self.servers[i] is not None:
            self.servers[i].kill()
            self.servers[i] = None

    # -- connectivity ----------------------------------------------------

    def _apply_edges(self) -> None:
        """Server-server edges on iff same partition side."""
        for i in range(self.n):
            for j in range(self.n):
                if self.endnames[i][j] is not None:
                    on = self.groups[i] == self.groups[j]
                    self.net.enable(self.endnames[i][j], on)

    def connect_all(self) -> None:
        self.groups = [0] * self.n
        self._apply_edges()

    def partition(self, p1: List[int], p2: List[int]) -> None:
        """2-way partition (reference: kvraft/config.go:177-189)."""
        for i in p1:
            self.groups[i] = 0
        for i in p2:
            self.groups[i] = 1
        self._apply_edges()

    def random_partition(self) -> None:
        """The GenericTest partitioner's random 2-way split
        (reference: kvraft/test_test.go:178-197)."""
        p1, p2 = [], []
        for i in range(self.n):
            (p1 if self.rng.random() < 0.5 else p2).append(i)
        self.partition(p1, p2)

    # -- clerks ----------------------------------------------------------

    def make_client(self) -> Clerk:
        """Clerk with its own endpoints and shuffled server order
        (reference: kvraft/config.go:194-212)."""
        self._next_clerk += 1
        cid = self._next_clerk
        order = list(range(self.n))
        self.rng.shuffle(order)
        ends = []
        names = []
        for j in order:
            name = ("ck", cid, j)
            end = self.net.make_end(name)
            self.net.connect(name, j)
            self.net.enable(name, True)
            ends.append(end)
            names.append(name)
        ck = Clerk(self.sched, ends)
        self.clerks[ck] = names
        return ck

    def connect_client(self, ck: Clerk, to: List[int]) -> None:
        """Restrict a clerk to a subset of servers
        (reference: kvraft/config.go ConnectClient)."""
        allowed = set(to)
        for name in self.clerks[ck]:
            _, _, j = name
            self.net.enable(name, j in allowed)

    def current_leader(self) -> int:
        """Index of the live server claiming leadership at the highest
        term; -1 if none."""
        best, best_term = -1, -1
        for i, s in enumerate(self.servers):
            if s is not None:
                term, is_leader = s.rf.get_state()
                if is_leader and term > best_term:
                    best, best_term = i, term
        return best

    # -- stats -----------------------------------------------------------

    def log_size(self) -> int:
        return max(p.raft_state_size() for p in self.saved)

    def snapshot_size(self) -> int:
        return max(p.snapshot_size() for p in self.saved)

    def op_total(self) -> int:
        return self.net.get_total_count()

    def cleanup(self) -> None:
        for s in self.servers:
            if s is not None:
                s.kill()
        self.net.cleanup()

    # -- sync helpers ----------------------------------------------------

    def run(self, gen):
        """Run a clerk coroutine to completion on the scheduler."""
        return self.sched.run_until(self.sched.spawn(gen))
