"""kvraft test fixture (reference: kvraft/config.go).

A thin wrapper over :class:`~multiraft_tpu.harness.cluster.Cluster`
adding kvraft clerk construction (reference: kvraft/config.go:194-212)
and the same partition/crash surface the reference exposes
(reference: kvraft/config.go:177-189,258-326).
"""

from __future__ import annotations

import random
from typing import List

from ..raft.persister import Persister
from ..services.kvraft import Clerk, KVServer
from ..sim.scheduler import Scheduler
from ..transport.network import Network
from .cluster import Cluster

__all__ = ["KVHarness"]


class KVHarness:
    def __init__(
        self,
        n: int,
        unreliable: bool = False,
        maxraftstate: int = -1,
        seed: int = 0,
    ) -> None:
        self.sched = Scheduler()
        self.net = Network(self.sched, seed=seed)
        self.net.set_reliable(not unreliable)
        self.n = n
        self.rng = random.Random(seed ^ 0xBEEF)
        self.maxraftstate = maxraftstate

        def factory(ends, i, persister: Persister, srv_seed: int):
            srv = KVServer(
                self.sched,
                ends,
                i,
                persister,
                maxraftstate=self.maxraftstate,
                seed=srv_seed,
            )
            return srv, {"KVServer": srv, "Raft": srv.rf}

        self.cluster = Cluster(
            self.sched, self.net, "kv", n, factory, self.rng, seed=seed
        )
        self.cluster.start_all()
        self._clerk_ids: dict = {}

    # -- delegation to the cluster ---------------------------------------

    @property
    def servers(self):
        return self.cluster.handles

    def start_server(self, i: int) -> None:
        self.cluster.start_server(i)

    def shutdown_server(self, i: int) -> None:
        self.cluster.shutdown_server(i)

    def connect_all(self) -> None:
        self.cluster.connect_all()

    def partition(self, p1: List[int], p2: List[int]) -> None:
        self.cluster.partition(p1, p2)

    def random_partition(self) -> None:
        self.cluster.random_partition()

    def current_leader(self) -> int:
        return self.cluster.current_leader()

    def log_size(self) -> int:
        return self.cluster.log_size()

    def snapshot_size(self) -> int:
        return self.cluster.snapshot_size()

    # -- clerks ----------------------------------------------------------

    def make_client(self) -> Clerk:
        ends = self.cluster.make_client_ends()
        ck = Clerk(self.sched, ends)
        self._clerk_ids[ck] = self.cluster._last_clerk_id
        return ck

    def connect_client(self, ck: Clerk, to: List[int]) -> None:
        self.cluster.restrict_client(self._clerk_ids[ck], to)

    # -- misc -------------------------------------------------------------

    def op_total(self) -> int:
        return self.net.get_total_count()

    def cleanup(self) -> None:
        self.cluster.kill_all()
        self.net.cleanup()

    def run(self, gen):
        return self.sched.run_until(self.sched.spawn(gen))
