"""Fleet scraper: one merged, clock-aligned timeline per run.

Every server process carries an ``"Obs"`` control service
(distributed/observe.py) exposing its metrics registry and trace
buffer.  :class:`FleetObserver` is the host side: it scrapes the whole
fleet over a chaos-free :class:`~multiraft_tpu.distributed.tcp.RpcNode`,
estimates each process's clock offset from scrape round trips, shifts
every remote event onto the host clock, and assembles ONE Chrome-trace
JSON where clerk spans (host process), server dispatch spans, engine
commit instants, and nemesis fault windows all line up on a shared
time axis — the "what was the fleet doing while that window was open"
view that per-process logs cannot give.

Clock alignment: ``Obs.clock`` returns the remote ``perf_counter`` in
µs.  For each process the observer takes several round trips and keeps
the offset measured at MINIMUM RTT (the sample least smeared by queue
delay): ``offset = remote_now − (t_send + t_recv)/2``.  Remote event
timestamps are then shifted by ``−offset``.  On one machine (the
process-cluster harness) the clocks share a timebase and offsets are
dominated by per-process ``perf_counter`` epochs — typically constant
to well under a millisecond, which is enough to order windows against
request spans.

Usage (the slow nemesis test is the canonical caller)::

    obs = FleetObserver(addrs)
    ...run nemesis + clerk load (collecting clerk events)...
    tracer = obs.merged_timeline(
        local_events=clerk_events, windows=nem.windows)
    tracer.save("trace_nemesis.json.gz")
    snaps = obs.snapshot_all()
    obs.close()
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..distributed.observe import now_us
from ..distributed.tcp import RpcNode
from ..sim.scheduler import TIMEOUT
from ..utils.trace import Tracer

__all__ = ["FleetObserver"]

Addr = Tuple[str, int]


class FleetObserver:
    """Scrapes ``Obs.*`` across a fleet and merges the results.

    The observer's own node carries no chaos, and ``Obs.*`` frames are
    control-exempt on the targets, so scrapes work mid-fault — a
    CRASHED process is unreachable and shows up as an explicit
    ``missing`` marker (and in :attr:`unreachable`), never as a
    silently shorter fleet."""

    def __init__(self, addrs: Sequence[Addr]) -> None:
        self.node = RpcNode()
        self.sched = self.node.sched
        self.addrs: List[Addr] = [tuple(a) for a in addrs]
        self.ends = {a: self.node.client_end(*a) for a in self.addrs}
        # addr -> best (min-RTT) clock offset estimate so far, µs.
        self.offsets: Dict[Addr, float] = {}
        # addr -> (pid, name) from the last successful snapshot — kept
        # so a process that later dies can still be identified in
        # postmortem bundles (its ring file is keyed by pid).
        self.idents: Dict[Addr, Tuple[int, str]] = {}
        self.unreachable: List[Addr] = []

    # -- raw scrape verbs --------------------------------------------------

    def call(
        self, addr: Addr, meth: str, args: Any = None,
        timeout: float = 2.0, retries: int = 3,
    ) -> Any:
        for attempt in range(retries):
            reply = self.sched.wait(
                self.ends[addr].call(f"Obs.{meth}", args), timeout
            )
            if reply is not None and reply is not TIMEOUT:
                return reply
            time.sleep(0.05 * (attempt + 1))
        return None

    def ping(self, addr: Addr) -> bool:
        return self.call(addr, "ping") == "pong"

    def snapshot(self, addr: Addr) -> Optional[Dict[str, Any]]:
        return self.call(addr, "snapshot")

    def snapshot_all(self) -> Dict[str, Dict[str, Any]]:
        """Scrape the whole fleet: ``{"host:port": snapshot}``.

        A process that died (or was partitioned from the scraper) gets
        an explicit ``{"missing": True, ...}`` marker instead of being
        silently absent — a postmortem that omits the dead process is
        hiding exactly the row that matters.  The marker carries the
        pid/name remembered from the last successful scrape, so the
        doctor can still pair the dead address with its on-disk flight
        ring."""
        out: Dict[str, Dict[str, Any]] = {}
        for a in self.addrs:
            key = f"{a[0]}:{a[1]}"
            snap = self.snapshot(a)
            if snap is not None:
                self.idents[a] = (int(snap.get("pid", -1)),
                                  str(snap.get("name", "")))
                out[key] = snap
            else:
                pid, name = self.idents.get(a, (-1, ""))
                out[key] = {"missing": True, "pid": pid, "name": name}
        return out

    def drain_trace(self, addr: Addr) -> Optional[Dict[str, Any]]:
        return self.call(addr, "trace", timeout=5.0)

    def hist(self, addr: Addr) -> Optional[Dict[str, Any]]:
        """One process's CUMULATIVE latency-histogram dumps + live
        queue gauges (``Obs.hist``).  Cumulative by design: callers
        window by diffing two scrapes (``Hist.sub``), so the scrape is
        read-only and concurrent observers can't clobber each other —
        harness/loadcurve.py is the aggregating caller."""
        return self.call(addr, "hist", timeout=5.0)

    def hist_all(self) -> Dict[str, Optional[Dict[str, Any]]]:
        """Scrape ``Obs.hist`` fleet-wide: ``{"host:port": dump}``,
        with ``None`` for unreachable processes (explicit, same as
        :meth:`snapshot_all`'s missing markers)."""
        return {f"{a[0]}:{a[1]}": self.hist(a) for a in self.addrs}

    def profile(
        self, addr: Addr, reset: bool = True
    ) -> Optional[Dict[str, Any]]:
        """One process's sampling-profiler aggregate (``Obs.profile``,
        profile.py).  Drain-on-read by default — each scrape returns
        exactly the samples taken since the previous one, the same
        windowing discipline the loadcurve uses; ``reset=False``
        peeks."""
        args = None if reset else {"reset": False}
        return self.call(addr, "profile", args, timeout=5.0)

    def profile_all(
        self, reset: bool = True
    ) -> Dict[str, Optional[Dict[str, Any]]]:
        """Scrape ``Obs.profile`` fleet-wide: ``{"host:port": reply}``,
        ``None`` for unreachable processes."""
        return {
            f"{a[0]}:{a[1]}": self.profile(a, reset) for a in self.addrs
        }

    def tail(
        self, addr: Addr, reset: bool = True
    ) -> Optional[Dict[str, Any]]:
        """One process's tail-exemplar store (``Obs.tail``, tail.py).
        Drain-on-read by default — same windowing discipline as
        :meth:`profile`; ``reset=False`` peeks (bundle collection)."""
        args = None if reset else {"reset": False}
        return self.call(addr, "tail", args, timeout=5.0)

    def tail_all(
        self, reset: bool = True
    ) -> Dict[str, Optional[Dict[str, Any]]]:
        """Scrape ``Obs.tail`` fleet-wide: ``{"host:port": reply}``,
        ``None`` for unreachable processes."""
        return {
            f"{a[0]}:{a[1]}": self.tail(a, reset) for a in self.addrs
        }

    @staticmethod
    def fleet_flame(
        dumps: Dict[str, Optional[Dict[str, Any]]],
    ) -> Dict[str, int]:
        """Merge per-process ``Obs.profile`` replies into ONE folded
        aggregate — the fleet flame.  Each process's stacks are
        prefixed with its Observability name (``pid123:9001;
        multiraft-loop/9001;tcp._run;...``), so one flamegraph shows
        the whole fleet with per-process, per-thread attribution.
        Unreachable (None) and not-profiling (``profile: None``)
        processes contribute nothing — the caller can tell them apart
        in ``dumps`` itself."""
        from ..distributed.profile import merge_folded

        parts: List[Dict[str, int]] = []
        for key, reply in dumps.items():
            prof = (reply or {}).get("profile")
            if not prof:
                continue
            name = str((reply or {}).get("name") or key)
            parts.append({
                f"{name};{stack}": n
                for stack, n in (prof.get("stacks") or {}).items()
            })
        return merge_folded(parts)

    @staticmethod
    def profile_counter_track(
        tracer: Tracer,
        dumps: Dict[str, Optional[Dict[str, Any]]],
        ts_us: Optional[float] = None,
    ) -> None:
        """Emit one Perfetto counter sample per process from a
        ``profile_all`` scrape — per-thread sample counts on a
        ``cpu_samples`` track (repeated scrapes across a sweep render
        as the fleet's CPU-attribution area chart next to the latency
        tracks)."""
        from ..distributed.profile import per_thread_totals

        if ts_us is None:
            ts_us = now_us()
        for pid, (key, reply) in enumerate(sorted(dumps.items())):
            prof = (reply or {}).get("profile")
            if not prof:
                continue
            totals = per_thread_totals(prof.get("stacks") or {})
            if totals:
                tracer.counter(
                    "cpu_samples", ts_us,
                    {t: float(n) for t, n in sorted(totals.items())},
                    pid=pid + 1, track="profile",
                )

    # -- clock alignment ---------------------------------------------------

    def clock_offset_us(
        self, addr: Addr, samples: int = 7,
    ) -> Optional[float]:
        """Min-RTT midpoint estimate of ``remote_clock − local_clock``
        (µs); ``None`` when the process is unreachable.  The freshest
        successful estimate is cached in :attr:`offsets` and reused
        when a later scrape finds the process unreachable."""
        best_rtt, best_off = None, None
        for _ in range(samples):
            t0 = now_us()
            remote = self.call(addr, "clock", retries=1, timeout=1.0)
            t1 = now_us()
            if remote is None:
                continue
            rtt = t1 - t0
            if best_rtt is None or rtt < best_rtt:
                best_rtt, best_off = rtt, float(remote) - (t0 + t1) / 2.0
        if best_off is None:
            return self.offsets.get(addr)
        self.offsets[addr] = best_off
        return best_off

    # -- merged timeline ---------------------------------------------------

    def merged_timeline(
        self,
        local_events: Sequence[Dict[str, Any]] = (),
        windows: Sequence[Dict[str, Any]] = (),
        schedule: Sequence[Tuple[float, str, Dict[str, Any]]] = (),
        t0_us: Optional[float] = None,
        local_name: str = "host (clerks + nemesis)",
    ) -> Tracer:
        """Drain every reachable process's trace buffer, shift each
        event onto the host clock, and return one :class:`Tracer`:

        * pid 0 — the host process: ``local_events`` verbatim (clerk
          request spans from :func:`~.nemesis.run_clerk_load`'s
          ``trace_sink``) plus one ``nemesis`` track annotating fault
          ``windows`` (:attr:`~.nemesis.Nemesis.windows` records, in
          host-clock µs already) and/or a planned ``schedule`` anchored
          at ``t0_us`` (:attr:`~.nemesis.Nemesis.t0_us`).
        * pid 1..N — one per fleet process, labelled with the remote
          ``Observability.name``, events shifted by the min-RTT clock
          offset.

        Unreachable processes are listed in :attr:`unreachable` AND get
        their own (empty) process row in the trace, labelled
        ``"MISSING"`` with an instant marking when the scrape failed —
        a merged trace must not silently present a partial fleet as
        the whole one."""
        parts: List[Tuple[Addr, float, Optional[Dict[str, Any]]]] = []
        self.unreachable = []
        for a in self.addrs:
            off = self.clock_offset_us(a)
            part = self.drain_trace(a) if off is not None else None
            if part is None:
                # Dead or partitioned: keep its slot in the merge (a
                # cached offset from an earlier scrape may survive).
                self.unreachable.append(a)
            parts.append((a, off if off is not None else 0.0, part))

        n_events = (
            len(local_events)
            + sum(len(p["events"]) for _, _, p in parts if p is not None)
            + 2 * (len(windows) + len(schedule))
            + 2 * len(parts)
            + 64
        )
        out = Tracer(max_events=n_events)
        out.process_name(0, local_name)
        for ev in local_events:
            ev = dict(ev)
            ev["pid"] = 0
            out._emit(ev)

        for i, (a, off, part) in enumerate(parts):
            pid = i + 1
            if part is None:
                label = self.idents.get(a, (-1, "?"))[1] or "?"
                out.process_name(pid, f"MISSING {label} @ {a[0]}:{a[1]}")
                out.instant(
                    "process_missing", now_us(),
                    track="obs", pid=pid, addr=f"{a[0]}:{a[1]}",
                )
                continue
            out.process_name(pid, f"{part.get('name')} @ {a[0]}:{a[1]}")
            for ev in part["events"]:
                ev = dict(ev)
                ev["ts"] = float(ev["ts"]) - off
                ev["pid"] = pid
                out._emit(ev)
            if part.get("dropped"):
                out.instant(
                    "trace_buffer_dropped",
                    float(part["now_us"]) - off,
                    track="obs", pid=pid, dropped=part["dropped"],
                )

        self._annotate(out, windows, schedule, t0_us)
        return out

    @staticmethod
    def _annotate(
        out: Tracer,
        windows: Sequence[Dict[str, Any]],
        schedule: Sequence[Tuple[float, str, Dict[str, Any]]],
        t0_us: Optional[float],
    ) -> None:
        """Fault windows onto pid 0's ``nemesis`` track: executed
        windows as spans (actual wall times + outcome args), planned
        schedule entries as instants (intent times)."""
        for w in windows:
            ts = float(w["t_start_us"])
            stop = w.get("t_stop_us")
            dur = max(0.0, float(stop) - ts) if stop is not None else 0.0
            args = {
                "acked": w.get("acked"), "hits": w.get("hits"),
                **{k: v for k, v in (w.get("p") or {}).items()},
            }
            if w.get("excused"):
                args["excused"] = w["excused"]
            if dur > 0:
                out.span(w["kind"], ts, dur, track="nemesis", pid=0, **args)
            else:
                out.instant(w["kind"], ts, track="nemesis", pid=0, **args)
        if t0_us is not None:
            for at, kind, p in schedule:
                out.instant(
                    f"plan:{kind}", t0_us + float(at) * 1e6,
                    track="nemesis-plan", pid=0,
                    **{k: v for k, v in (p or {}).items()},
                )

    def close(self) -> None:
        self.node.close()
