"""Raft test fixture — the ``raft/config.go`` equivalent (reference:
raft/config.go:69-142,283-340,438-619).

Builds n Raft peers in one simulated network with a fresh endpoint matrix
per incarnation, so crash/restart leaves *zombie instances* whose RPCs
can never land again (reference: raft/config.go:113-142) — the old node
object keeps firing timers harmlessly, exactly like the reference's
abandoned goroutines.

Invariant appliers cross-check every committed (index, command) pair
across all servers and enforce in-order apply
(reference: raft/config.go:144-186), and the snapshot applier
additionally snapshots every ``SNAPSHOT_INTERVAL`` applies and enforces
contiguity (reference: raft/config.go:215-274).
"""

from __future__ import annotations

import random
from typing import Any, List, Optional

from ..raft.messages import ApplyMsg
from ..raft.node import RaftNode
from ..raft.persister import Persister
from ..sim.scheduler import Scheduler
from ..transport import codec
from ..transport.network import Network, Server, Service

__all__ = ["RaftHarness", "SNAPSHOT_INTERVAL", "MAX_LOG_SIZE"]

SNAPSHOT_INTERVAL = 10  # (reference: raft/config.go:215)
MAX_LOG_SIZE = 2000  # 2D log-size gate (reference: raft/test_test.go:1110)


class HarnessError(AssertionError):
    pass


class RaftHarness:
    def __init__(
        self,
        n: int,
        unreliable: bool = False,
        snapshot: bool = False,
        seed: int = 0,
        prevote: bool = False,
    ) -> None:
        self.prevote = prevote
        self.sched = Scheduler()
        self.net = Network(self.sched, seed=seed)
        # Budget accounting: the harness and network share one Metrics
        # registry (utils/metrics.py) — RPC/byte totals accumulate
        # there, and one() records agreement latency in virtual time.
        self.metrics = self.net.metrics
        self.net.set_reliable(not unreliable)
        self.n = n
        self.seed = seed
        self.rng = random.Random(seed ^ 0xC0FFEE)
        self.use_snapshot = snapshot
        self.rafts: List[Optional[RaftNode]] = [None] * n
        self.saved: List[Persister] = [Persister() for _ in range(n)]
        self.connected = [False] * n
        self.endnames: List[List[Any]] = [[None] * n for _ in range(n)]
        self._incarnation = 0
        self.logs: List[dict] = [dict() for _ in range(n)]
        self.max_index = 0
        self.apply_err: Optional[str] = None
        self.max_command_index_seen = 0
        for i in range(n):
            self.start1(i)
        for i in range(n):
            self.connect(i)

    # -- lifecycle (reference: raft/config.go:113-142,283-340) ------------

    def crash1(self, i: int) -> None:
        """Crash server i: cut it off, suppress in-flight replies, and
        snapshot its persister so a restart sees exactly what it saved."""
        self.disconnect(i)
        self.net.delete_server(i)
        self.saved[i] = self.saved[i].copy()
        if self.rafts[i] is not None:
            self.rafts[i].kill()
            self.rafts[i] = None

    def start1(self, i: int) -> None:
        """(Re)start server i from its persisted state with a brand-new
        endpoint matrix — the previous incarnation becomes a zombie."""
        if self.rafts[i] is not None:
            self.crash1(i)
        self._incarnation += 1
        inc = self._incarnation
        ends = []
        for j in range(self.n):
            name = (i, j, inc)
            self.endnames[i][j] = name
            end = self.net.make_end(name)
            self.net.connect(name, j)
            ends.append(end)
        persister = self.saved[i].copy()
        self.saved[i] = persister
        self.logs[i] = {}

        if self.use_snapshot:
            apply_fn = self._make_applier_snap(i)
        else:
            apply_fn = self._make_applier(i)
        raft = RaftNode(
            self.sched, ends, i, persister, apply_fn,
            seed=self.seed * 131 + inc, prevote=self.prevote,
        )
        self.rafts[i] = raft
        if self.use_snapshot:
            restored = self._install_harness_snapshot(
                i, persister.read_snapshot()
            )
            self._snap_applier_state["last"] = restored
        srv = Server()
        srv.add_service(Service(raft, name="Raft"))
        self.net.add_server(i, srv)
        for j in range(self.n):
            self.net.enable(self.endnames[i][j], False)

    def connect(self, i: int) -> None:
        """(reference: raft/config.go:366-409 per-edge enable)"""
        self.connected[i] = True
        for j in range(self.n):
            if self.connected[j]:
                self.net.enable(self.endnames[i][j], True)
                self.net.enable(self.endnames[j][i], True)

    def disconnect(self, i: int) -> None:
        self.connected[i] = False
        for j in range(self.n):
            if self.endnames[i][j] is not None:
                self.net.enable(self.endnames[i][j], False)
            if self.endnames[j][i] is not None:
                self.net.enable(self.endnames[j][i], False)

    def cleanup(self) -> None:
        for r in self.rafts:
            if r is not None:
                r.kill()
        self.net.cleanup()
        if self.apply_err:
            raise HarnessError(self.apply_err)

    # -- invariant appliers (reference: raft/config.go:144-274) -----------

    def _check_logs(self, i: int, m: ApplyMsg) -> Optional[str]:
        v = m.command
        for j in range(self.n):
            old = self.logs[j].get(m.command_index)
            if old is not None and old != v:
                return (
                    f"commit index={m.command_index} server={i} {v} != "
                    f"server={j} {old}"
                )
        prev_ok = (m.command_index - 1) in self.logs[i] or m.command_index <= 1
        self.logs[i][m.command_index] = v
        if m.command_index > self.max_index:
            self.max_index = m.command_index
        if not prev_ok:
            return f"server {i} apply out of order {m.command_index}"
        return None

    def _make_applier(self, i: int):
        def apply_fn(m: ApplyMsg) -> None:
            if not m.command_valid:
                return
            err = self._check_logs(i, m)
            if err and self.apply_err is None:
                self.apply_err = err

        return apply_fn

    def _install_harness_snapshot(self, i: int, data: bytes) -> int:
        if not data:
            return 0
        blob = codec.decode(data)
        self.logs[i] = {idx + 1: v for idx, v in enumerate(blob["xlog"])}
        return blob["last_index"]

    def _make_applier_snap(self, i: int):
        """Applier that snapshots every SNAPSHOT_INTERVAL applies and
        enforces contiguous apply (reference: raft/config.go:215-274)."""
        state = {"last": 0}
        self._snap_applier_state = state  # resynced by start1 on restart

        def apply_fn(m: ApplyMsg) -> None:
            if m.snapshot_valid:
                state["last"] = self._install_harness_snapshot(i, m.snapshot)
                return
            if not m.command_valid:
                return
            if m.command_index != state["last"] + 1 and self.apply_err is None:
                self.apply_err = (
                    f"server {i} apply out of order, expected index "
                    f"{state['last'] + 1}, got {m.command_index}"
                )
                return
            err = self._check_logs(i, m)
            if err and self.apply_err is None:
                self.apply_err = err
                return
            state["last"] = m.command_index
            if m.command_index % SNAPSHOT_INTERVAL == 0:
                xlog = [
                    self.logs[i][k] for k in range(1, m.command_index + 1)
                ]
                blob = codec.encode(
                    {"last_index": m.command_index, "xlog": xlog}
                )
                raft = self.rafts[i]
                if raft is not None:
                    raft.snapshot(m.command_index, blob)

        return apply_fn

    # -- checks (reference: raft/config.go:438-619) -----------------------

    def check_one_leader(self) -> int:
        for _ in range(10):
            self.sched.run_for(self.rng.uniform(0.45, 0.55))
            leaders: dict[int, list[int]] = {}
            for i in range(self.n):
                if self.connected[i] and self.rafts[i] is not None:
                    term, is_leader = self.rafts[i].get_state()
                    if is_leader:
                        leaders.setdefault(term, []).append(i)
            last_term_with_leader = -1
            for term, who in leaders.items():
                if len(who) > 1:
                    raise HarnessError(
                        f"term {term} has {len(who)} (>1) leaders"
                    )
                last_term_with_leader = max(last_term_with_leader, term)
            if leaders:
                return leaders[last_term_with_leader][0]
        raise HarnessError("expected one leader, got none")

    def check_terms(self) -> int:
        term = -1
        for i in range(self.n):
            if self.connected[i] and self.rafts[i] is not None:
                t, _ = self.rafts[i].get_state()
                if term == -1:
                    term = t
                elif term != t:
                    raise HarnessError("servers disagree on term")
        return term

    def check_no_leader(self) -> None:
        for i in range(self.n):
            if self.connected[i] and self.rafts[i] is not None:
                _, is_leader = self.rafts[i].get_state()
                if is_leader:
                    raise HarnessError(
                        f"expected no leader, but {i} claims to be leader"
                    )

    def n_committed(self, index: int) -> tuple[int, Any]:
        count, cmd = 0, None
        for i in range(self.n):
            if self.apply_err:
                raise HarnessError(self.apply_err)
            v = self.logs[i].get(index)
            if v is not None:
                if count > 0 and cmd != v:
                    raise HarnessError(
                        f"committed values do not match: index {index}, "
                        f"{cmd}, {v}"
                    )
                count += 1
                cmd = v
        return count, cmd

    def wait(self, index: int, n: int, start_term: int) -> Any:
        """(reference: raft/config.go:528-555)"""
        to = 0.01
        for _ in range(30):
            nd, _ = self.n_committed(index)
            if nd >= n:
                break
            self.sched.run_for(to)
            if to < 1.0:
                to *= 2
            if start_term > -1:
                for r in self.rafts:
                    if r is not None:
                        t, _ = r.get_state()
                        if t > start_term:
                            return -1  # term moved on; can't guarantee
        nd, cmd = self.n_committed(index)
        if nd < n:
            raise HarnessError(
                f"only {nd} decided for index {index}; wanted {n}"
            )
        return cmd

    def one(self, cmd: Any, expected_servers: int, retry: bool) -> int:
        """Submit until agreed (reference: raft/config.go:569-619)."""
        t0 = self.sched.now
        starts = 0
        while self.sched.now - t0 < 10.0:
            index = -1
            for _ in range(self.n):
                starts = (starts + 1) % self.n
                rf = self.rafts[starts]
                if self.connected[starts] and rf is not None:
                    ix, _, ok = rf.start(cmd)
                    if ok:
                        index = ix
                        break
            if index != -1:
                t1 = self.sched.now
                while self.sched.now - t1 < 2.0:
                    nd, cmd1 = self.n_committed(index)
                    if nd >= expected_servers and cmd1 == cmd:
                        self.metrics.inc("one_agreements")
                        self.metrics.observe("one_latency_s", self.sched.now - t0)
                        return index
                    self.sched.run_for(0.02)
                if not retry:
                    raise HarnessError(f"one({cmd!r}) failed to reach agreement")
            else:
                self.sched.run_for(0.05)
        raise HarnessError(f"one({cmd!r}) failed to reach agreement (timeout)")

    # -- stats ------------------------------------------------------------

    def rpc_count(self, server: int) -> int:
        return self.net.get_count(server)

    def rpc_total(self) -> int:
        return self.net.get_total_count()

    def bytes_total(self) -> int:
        return self.net.get_total_bytes()

    def log_size(self) -> int:
        return max(p.raft_state_size() for p in self.saved)
