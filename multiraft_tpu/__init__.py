"""multiraft_tpu — a TPU-native multi-Raft framework.

A ground-up rebuild of the capabilities of ``yusong-yan/MultiRaft`` (an
MIT-6.824-style Go stack: simulated fault-injecting RPC network, complete
Raft, linearizable KV, shard controller, sharded multi-group KV, porcupine
linearizability checker) designed for JAX/XLA/Pallas:

* ``sim``       — deterministic virtual-time event loop (the host runtime)
* ``transport`` — fault-injecting network + codec (labrpc/labgob equiv)
* ``raft``      — single-group event-driven Raft (the correctness oracle)
* ``services``  — kvraft, shardctrler, shardkv replicated state machines
* ``porcupine`` — linearizability checker + KV model + visualizer
* ``engine``    — the batched TPU consensus engine: a jit tick function
                  over ``(groups, peers)`` state tensors, Pallas kernels
                  for quorum-commit/vote-tally hot ops
* ``harness``   — test fixtures: partitions, crashes, churn drivers
* ``distributed`` — real deployment: epoll TCP transport (C++ core),
                  wall-clock scheduler, checksummed disk persister,
                  multi-process KV and sharded clusters
* ``utils``     — config system, metrics registry, Chrome-trace tracer,
                  cross-process client identity
"""

__version__ = "0.1.0"
