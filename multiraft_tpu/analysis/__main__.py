"""CLI: ``python -m multiraft_tpu.analysis [paths...]``.

Exit status 1 on any unsuppressed finding, 0 otherwise.  Suppressed
findings (``# graftlint: disable=<rule>``) are listed with ``-v`` so
the suppression inventory stays reviewable.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import ALL_RULES, run
from . import rules as _rules  # noqa: F401
from . import lockgraph as _lockgraph  # noqa: F401
from . import dataflow as _dataflow  # noqa: F401
from . import planes as _planes  # noqa: F401
from . import registry as _registry  # noqa: F401


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint")
    ap.add_argument(
        "paths",
        nargs="*",
        default=["multiraft_tpu"],
        help="files or directories to lint (default: multiraft_tpu)",
    )
    ap.add_argument(
        "-v",
        "--verbose",
        action="store_true",
        help="also list suppressed findings",
    )
    ap.add_argument(
        "--rule",
        action="append",
        default=None,
        help="run only the named rule(s)",
    )
    ap.add_argument(
        "--format",
        choices=("text", "github"),
        default="text",
        help=(
            "finding output format: github emits ::error annotation "
            "lines that render inline on PRs"
        ),
    )
    ap.add_argument(
        "--timings",
        action="store_true",
        help="print per-rule wall-clock timing to stderr",
    )
    ap.add_argument(
        "--per-rule",
        action="store_true",
        help=(
            "print per-rule active/suppressed finding counts to "
            "stderr (the suppression inventory at a glance)"
        ),
    )
    ns = ap.parse_args(argv)
    rules = ALL_RULES
    if ns.rule:
        rules = [r for r in ALL_RULES if r.name in ns.rule]
        if not rules:
            known = ", ".join(sorted(r.name for r in ALL_RULES))
            print(f"graftlint: no such rule(s); known: {known}",
                  file=sys.stderr)
            return 2
    timings = {} if ns.timings else None
    active, suppressed = run(
        [Path(p) for p in ns.paths], rules, timings=timings
    )
    for f in active:
        if ns.format == "github":
            # GitHub workflow-command annotation: shows inline on the PR
            # diff.  Message must be single-line (newlines end the
            # command) and paths repo-relative.
            msg = f.message.replace("\n", " ")
            print(
                f"::error file={f.path},line={f.line},"
                f"title=graftlint/{f.rule}::{msg}"
            )
        else:
            print(f)
    if timings is not None:
        for rname, secs in sorted(
            timings.items(), key=lambda kv: -kv[1]
        ):
            print(f"  rule {rname:<22} {secs * 1000:8.1f} ms",
                  file=sys.stderr)
    if ns.per_rule:
        counts = {r.name: [0, 0] for r in rules}
        for f in active:
            counts[f.rule][0] += 1
        for f in suppressed:
            counts[f.rule][1] += 1
        for rname in sorted(counts):
            a, s = counts[rname]
            print(
                f"  rule {rname:<22} {a:3d} active {s:3d} suppressed",
                file=sys.stderr,
            )
    if ns.verbose and suppressed:
        print(f"-- {len(suppressed)} suppressed --")
        for f in suppressed:
            print(f"  {f}")
    if active:
        print(
            f"graftlint: {len(active)} finding(s) "
            f"({len(suppressed)} suppressed)",
            file=sys.stderr,
        )
        return 1
    print(
        f"graftlint: clean ({len(ALL_RULES) if rules is ALL_RULES else len(rules)}"
        f" rules, {len(suppressed)} suppressed finding(s))",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
