"""CLI: ``python -m multiraft_tpu.analysis [paths...]``.

Exit status 1 on any unsuppressed finding, 0 otherwise.  Suppressed
findings (``# graftlint: disable=<rule>``) are listed with ``-v`` so
the suppression inventory stays reviewable.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import ALL_RULES, run
from . import rules as _rules  # noqa: F401
from . import lockgraph as _lockgraph  # noqa: F401


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint")
    ap.add_argument(
        "paths",
        nargs="*",
        default=["multiraft_tpu"],
        help="files or directories to lint (default: multiraft_tpu)",
    )
    ap.add_argument(
        "-v",
        "--verbose",
        action="store_true",
        help="also list suppressed findings",
    )
    ap.add_argument(
        "--rule",
        action="append",
        default=None,
        help="run only the named rule(s)",
    )
    ns = ap.parse_args(argv)
    rules = ALL_RULES
    if ns.rule:
        rules = [r for r in ALL_RULES if r.name in ns.rule]
        if not rules:
            known = ", ".join(sorted(r.name for r in ALL_RULES))
            print(f"graftlint: no such rule(s); known: {known}",
                  file=sys.stderr)
            return 2
    active, suppressed = run([Path(p) for p in ns.paths], rules)
    for f in active:
        print(f)
    if ns.verbose and suppressed:
        print(f"-- {len(suppressed)} suppressed --")
        for f in suppressed:
            print(f"  {f}")
    if active:
        print(
            f"graftlint: {len(active)} finding(s) "
            f"({len(suppressed)} suppressed)",
            file=sys.stderr,
        )
        return 1
    print(
        f"graftlint: clean ({len(ALL_RULES) if rules is ALL_RULES else len(rules)}"
        f" rules, {len(suppressed)} suppressed finding(s))",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
