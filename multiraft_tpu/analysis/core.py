"""graftlint core: project-native AST lint framework.

The reference Go stack keeps a heavily concurrent consensus codebase
honest with ``go vet`` and the race detector; this package is the
Python/JAX port's equivalent, except the rules are *project-specific*:
each one encodes a bug class this codebase actually shipped (see
CHANGES.md PR 1-2 and docs/ARCHITECTURE.md §11).

Design:

* A :class:`Project` parses every ``.py`` file under the requested
  paths once (``ast.parse`` — files are never imported, so linting
  cannot execute project code or require heavyweight deps).
* A :class:`Rule` sees the whole project and yields
  :class:`Finding` objects.  Rules are whole-project rather than
  per-file because half of them are cross-file by nature (frame
  arities between encoder and decoder, service registrations vs. the
  chaos exemption set, the lock acquisition graph).
* Suppression is inline and auditable: ``# graftlint: disable=<rule>``
  on the offending line suppresses that rule there;
  ``# graftlint: disable-file=<rule>`` anywhere in a file suppresses
  the rule for the file.  ``run()`` returns suppressed findings
  separately so the test suite can assert suppressions stay few and
  documented.
"""

from __future__ import annotations

import ast
import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "ModuleInfo",
    "Project",
    "Rule",
    "run",
    "ALL_RULES",
    "register",
]

_PRAGMA_LINE = re.compile(r"#\s*graftlint:\s*disable=([\w,-]+)")
_PRAGMA_FILE = re.compile(r"#\s*graftlint:\s*disable-file=([\w,-]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class ModuleInfo:
    """One parsed source file plus its suppression pragmas."""

    path: Path
    source: str
    tree: ast.Module
    # line number -> set of rule names disabled on that line
    line_disables: Dict[int, Set[str]] = field(default_factory=dict)
    # rule names disabled for the whole file
    file_disables: Set[str] = field(default_factory=set)

    @property
    def name(self) -> str:
        return self.path.stem

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disables:
            return True
        return rule in self.line_disables.get(line, ())


class Project:
    """All parsed modules under the linted roots."""

    def __init__(self, modules: Sequence[ModuleInfo]) -> None:
        self.modules: List[ModuleInfo] = list(modules)
        self._by_stem: Dict[str, List[ModuleInfo]] = {}
        for m in self.modules:
            self._by_stem.setdefault(m.name, []).append(m)

    def find(self, stem: str) -> List[ModuleInfo]:
        """Modules whose filename (sans .py) is ``stem``."""
        return self._by_stem.get(stem, [])

    @classmethod
    def load(cls, paths: Iterable[Path]) -> "Project":
        files: List[Path] = []
        for p in paths:
            p = Path(p)
            if p.is_dir():
                files.extend(sorted(p.rglob("*.py")))
            elif p.suffix == ".py":
                files.append(p)
        mods = []
        for f in files:
            if "__pycache__" in f.parts:
                continue
            mod = _parse_module(f)
            if mod is not None:
                mods.append(mod)
        return cls(mods)


def _parse_module(path: Path) -> Optional[ModuleInfo]:
    try:
        source = path.read_text(encoding="utf-8")
    except OSError:
        return None
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        # A file that does not parse is itself a finding-worthy state,
        # but tier-1 pytest already fails on import errors; skip here.
        raise SyntaxError(f"{path}: {e}") from e
    line_disables: Dict[int, Set[str]] = {}
    file_disables: Set[str] = set()
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_FILE.search(line)
        if m:
            file_disables.update(m.group(1).split(","))
            continue
        m = _PRAGMA_LINE.search(line)
        if m:
            line_disables.setdefault(i, set()).update(m.group(1).split(","))
    return ModuleInfo(
        path=path,
        source=source,
        tree=tree,
        line_disables=line_disables,
        file_disables=file_disables,
    )


class Rule:
    """Base class: subclass, set ``name``/``doc``, implement ``check``."""

    name: str = "abstract"
    doc: str = ""

    def check(self, project: Project) -> List[Finding]:
        raise NotImplementedError


ALL_RULES: List[Rule] = []


def register(rule_cls):
    """Class decorator adding an instance to the default rule set."""
    ALL_RULES.append(rule_cls())
    return rule_cls


def run(
    paths: Iterable[Path],
    rules: Optional[Sequence[Rule]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Lint ``paths``; returns ``(active, suppressed)`` findings.

    ``active`` are unsuppressed violations (the gate fails on any);
    ``suppressed`` were matched by a ``# graftlint: disable`` pragma
    and are reported so suppressions stay visible.  Pass a dict as
    ``timings`` to collect per-rule wall-clock seconds (the first rule
    that touches the dataflow cache pays its build cost).
    """
    project = Project.load(paths)
    if rules is None:
        # Import for the registration side effect only.
        from . import rules as _rules  # noqa: F401
        from . import lockgraph as _lockgraph  # noqa: F401
        from . import dataflow as _dataflow  # noqa: F401
        from . import planes as _planes  # noqa: F401
        from . import registry as _registry  # noqa: F401

        rules = ALL_RULES
    by_path = {str(m.path): m for m in project.modules}
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for rule in rules:
        t0 = time.perf_counter() if timings is not None else 0.0
        findings = rule.check(project)
        if timings is not None:
            timings[rule.name] = (
                timings.get(rule.name, 0.0) + time.perf_counter() - t0
            )
        for f in findings:
            mod = by_path.get(f.path)
            if mod is not None and mod.is_suppressed(f.rule, f.line):
                suppressed.append(f)
            else:
                active.append(f)
    key = lambda f: (f.path, f.line, f.rule)  # noqa: E731
    # rules may visit nested functions from both enclosing scopes;
    # Finding is frozen/hashable so dedup is exact
    return (
        sorted(set(active), key=key),
        sorted(set(suppressed), key=key),
    )


# ---------------------------------------------------------------------------
# Shared AST helpers used by the rule modules.
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_int(node: ast.AST) -> Optional[int]:
    """Evaluate small constant integer expressions (``2 ** 16``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.BinOp):
        left, right = const_int(node.left), const_int(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Pow):
                return left**right if right < 128 else None
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
        except Exception:  # pragma: no cover - defensive
            return None
    return None


def iter_functions(tree: ast.Module):
    """Yield every (possibly nested) function/lambda-free def node."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def names_in(node: ast.AST) -> Set[str]:
    """All Name identifiers loaded anywhere inside ``node``."""
    return {
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    }
