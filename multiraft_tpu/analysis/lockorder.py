"""Dynamic lock-order recorder: the runtime cross-check for the
static audit in :mod:`.lockgraph`.

Go's race detector instruments every acquisition; we cannot, but we
can wrap the handful of *named* locks in the transport stack and
record the observed acquisition-order graph while the chaos tests
drive real traffic.  If the graph ever contains a cycle, two threads
can interleave into an ABBA deadlock even if no run has hung yet.

Usage (see tests/test_chaos.py)::

    rec = LockOrderRecorder()
    rec.wrap(node, "_lock", "RpcNode._lock")
    rec.wrap(node._tr, "_lock", "NativeTransport._lock")
    ... drive traffic ...
    rec.assert_acyclic()

The wrapper is a transparent proxy installed on the *instance*
attribute, so only the objects under test pay the (tiny) bookkeeping
cost; nothing global is monkeypatched.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

__all__ = ["LockOrderRecorder", "RecordingLock"]


class RecordingLock:
    """Proxy around a ``threading.Lock``-like object that reports
    acquire/release to a :class:`LockOrderRecorder`."""

    def __init__(self, inner, label: str, rec: "LockOrderRecorder") -> None:
        self._inner = inner
        self._label = label
        self._rec = rec

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._rec._acquired(self._label)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._rec._released(self._label)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class LockOrderRecorder:
    """Observed acquisition-order graph across all threads."""

    def __init__(
        self,
        on_edge: Optional[Callable[[str, str, str], None]] = None,
    ) -> None:
        self._tls = threading.local()
        self._mu = threading.Lock()
        # (held_label, acquired_label) → witness thread name
        self.edges: Dict[Tuple[str, str], str] = {}
        # Called once per NEW edge as (held, acquired, thread_name),
        # outside the recorder's own lock — the runtime sanitizer uses
        # it to check acyclicity as edges appear instead of only at
        # test teardown.
        self._on_edge = on_edge

    # -- wiring ------------------------------------------------------------

    def wrap(self, obj, attr: str, label: Optional[str] = None) -> None:
        """Replace ``obj.<attr>`` with a recording proxy."""
        label = label or f"{type(obj).__name__}.{attr}"
        inner = getattr(obj, attr)
        if isinstance(inner, RecordingLock):  # idempotent
            return
        setattr(obj, attr, RecordingLock(inner, label, self))

    # -- recording (called from RecordingLock) -----------------------------

    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _acquired(self, label: str) -> None:
        st = self._stack()
        if st:
            new = [
                (h, label)
                for h in st
                if h != label and (h, label) not in self.edges
            ]
            if new:
                tname = threading.current_thread().name
                inserted: List[Tuple[str, str]] = []
                with self._mu:
                    for key in new:
                        if key not in self.edges:
                            self.edges[key] = tname
                            inserted.append(key)
                if self._on_edge is not None:
                    for held, acq in inserted:
                        self._on_edge(held, acq, tname)
        st.append(label)

    def _released(self, label: str) -> None:
        st = self._stack()
        # locks may release out of LIFO order; drop the last occurrence
        for i in range(len(st) - 1, -1, -1):
            if st[i] == label:
                del st[i]
                break

    # -- queries -----------------------------------------------------------

    def cycle(self) -> Optional[List[str]]:
        """One observed acquisition-order cycle, or None."""
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        stack: List[str] = []

        def dfs(n: str) -> Optional[List[str]]:
            color[n] = GREY
            stack.append(n)
            for m in graph.get(n, ()):  # noqa: B007
                if color[m] == GREY:
                    return stack[stack.index(m):] + [m]
                if color[m] == WHITE:
                    got = dfs(m)
                    if got:
                        return got
            stack.pop()
            color[n] = BLACK
            return None

        for n in list(graph):
            if color[n] == WHITE:
                got = dfs(n)
                if got:
                    return got
        return None

    def assert_acyclic(self) -> None:
        cyc = self.cycle()
        if cyc is not None:
            raise AssertionError(
                "observed lock acquisition-order cycle (potential ABBA "
                f"deadlock): {' -> '.join(cyc)}; edges={sorted(self.edges)}"
            )
