"""graftlint v3: engine state-plane lifecycle rules.

The engine's persistence discipline lives in four hand-synced sites
(checkpoint save/restore, ``restart_replica``, ``reset_replica``, the
cross-replica column clears) plus one declared source of truth:
``engine/state_planes.py``.  These rules verify the sites against the
declaration statically — PR 15 (voted_for preserved across restart)
and PR 16 (stale votes/match columns on re-add) were exactly the bug
classes caught here.

* ``plane-class`` — every ``EngineState`` / ``Mailbox`` field carries
  a classification in ``STATE_PLANES`` / ``MAILBOX_PLANES``; stale
  registry entries (field removed, classification kept) are findings
  too, as are classifications outside the four planes.
* ``plane-lifecycle`` — ``restart_replica`` must reset every VOLATILE
  plane and touch nothing PERSISTENT or CONFIG; ``reset_replica`` must
  wipe every plane except the engine-global clock and CONFIG, and for
  each declared ``CROSS_COLUMNS`` field additionally clear the
  ``[g, :, p]`` column (stale votes/match/acks about the reborn peer).

Approximations (documented in ARCHITECTURE §11): both rules activate
only when a module declaring ``STATE_PLANES`` is in the linted
project, so fixture stubs of ``EngineState`` elsewhere stay silent;
the lifecycle rule reads the ``st._replace(field=...)`` keyword set,
so a lifecycle function with no ``_replace`` call (harness wrappers
that delegate over RPC) is out of scope; a cross-column clear is
recognized as an ``.at[...]`` subscript whose index tuple has a slice
in position 1 (``[g, :, p]``); Mailbox lifecycle masking goes through
``_mask_edges``/``mask_active`` and is checked at runtime, not here.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo, Project, Rule, register

_PLANE_VALUES = {"persistent", "volatile", "leadership", "config"}
_STATE_CLASSES = ("EngineState", "Mailbox")


class _Registry:
    """One parsed ``state_planes``-style declaration module."""

    def __init__(self, mod: ModuleInfo) -> None:
        self.mod = mod
        self.state_planes: Dict[str, str] = {}
        self.mailbox_planes: Dict[str, str] = {}
        self.cross_columns: Tuple[str, ...] = ()
        self.global_fields: Tuple[str, ...] = ()
        self.lines: Dict[str, int] = {}  # table name -> def line
        self.entry_lines: Dict[Tuple[str, str], int] = {}

    @property
    def planes_of(self) -> Dict[str, Dict[str, str]]:
        return {"EngineState": self.state_planes,
                "Mailbox": self.mailbox_planes}


def _str_consts(mod: ModuleInfo) -> Dict[str, str]:
    """Top-level ``NAME = "literal"`` bindings (the plane constants)."""
    out: Dict[str, str] = {}
    for stmt in mod.tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            vals.append(el.value)
        return tuple(vals)
    return None


def find_registry(project: Project) -> Optional[_Registry]:
    """The project's plane declaration: the module assigning a dict
    literal to ``STATE_PLANES`` at top level (None when absent — the
    plane rules then stay silent, so fixture stubs don't misfire)."""
    for mod in project.modules:
        tables: Dict[str, ast.Dict] = {}
        tuples: Dict[str, Tuple[str, ...]] = {}
        lines: Dict[str, int] = {}
        for stmt in mod.tree.body:
            tgt = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                tgt = stmt.target
                value = stmt.value
            else:
                continue
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id in ("STATE_PLANES", "MAILBOX_PLANES") and isinstance(
                value, ast.Dict
            ):
                tables[tgt.id] = value
                lines[tgt.id] = stmt.lineno
            elif tgt.id in ("CROSS_COLUMNS", "GLOBAL_FIELDS"):
                st = _str_tuple(value)
                if st is not None:
                    tuples[tgt.id] = st
                    lines[tgt.id] = stmt.lineno
        if "STATE_PLANES" not in tables:
            continue
        reg = _Registry(mod)
        reg.lines = lines
        consts = _str_consts(mod)
        for tname, node in tables.items():
            table: Dict[str, str] = {}
            for k, v in zip(node.keys, node.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    plane = v.value
                elif isinstance(v, ast.Name):
                    plane = consts.get(v.id, v.id)
                else:
                    plane = "?"
                table[k.value] = plane
                reg.entry_lines[(tname, k.value)] = k.lineno
            if tname == "STATE_PLANES":
                reg.state_planes = table
            else:
                reg.mailbox_planes = table
        reg.cross_columns = tuples.get("CROSS_COLUMNS", ())
        reg.global_fields = tuples.get("GLOBAL_FIELDS", ())
        return reg
    return None


def _namedtuple_fields(
    project: Project,
) -> List[Tuple[ModuleInfo, str, List[Tuple[str, int]]]]:
    """Every EngineState/Mailbox NamedTuple class in the project as
    ``(module, class_name, [(field, line), ...])``."""
    out = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if node.name not in _STATE_CLASSES:
                continue
            fields = [
                (st.target.id, st.lineno)
                for st in node.body
                if isinstance(st, ast.AnnAssign)
                and isinstance(st.target, ast.Name)
            ]
            if fields:
                out.append((mod, node.name, fields))
    return out


@register
class PlaneClassRule(Rule):
    name = "plane-class"
    doc = (
        "every EngineState/Mailbox field must carry a plane "
        "classification in engine/state_planes.py (and no stale "
        "entry may outlive its field)"
    )

    def check(self, project: Project) -> List[Finding]:
        reg = find_registry(project)
        if reg is None:
            return []
        out: List[Finding] = []
        seen_classes: Set[str] = set()
        for mod, cls_name, fields in _namedtuple_fields(project):
            seen_classes.add(cls_name)
            table = reg.planes_of[cls_name]
            tname = ("STATE_PLANES" if cls_name == "EngineState"
                     else "MAILBOX_PLANES")
            if not table:
                out.append(Finding(
                    rule=self.name, path=str(mod.path), line=1,
                    message=f"{cls_name} has no {tname} table in the "
                            f"plane registry ({reg.mod.path.name})",
                ))
                continue
            declared = set(table)
            names = {f for f, _ in fields}
            for f, line in fields:
                if f not in declared:
                    out.append(Finding(
                        rule=self.name, path=str(mod.path), line=line,
                        message=(
                            f"{cls_name} field '{f}' is unclassified: add "
                            f"it to {tname} in {reg.mod.path.name} "
                            f"(persistent/volatile/leadership/config) and "
                            f"bump CKPT_VERSION if the checkpoint schema "
                            f"changed"
                        ),
                    ))
            for f in sorted(declared - names):
                out.append(Finding(
                    rule=self.name, path=str(reg.mod.path),
                    line=reg.entry_lines.get((tname, f), reg.lines[tname]),
                    message=f"{tname} entry '{f}' names no {cls_name} "
                            f"field (stale classification)",
                ))
            for f in sorted(declared & names):
                if table[f] not in _PLANE_VALUES:
                    out.append(Finding(
                        rule=self.name, path=str(reg.mod.path),
                        line=reg.entry_lines.get(
                            (tname, f), reg.lines[tname]),
                        message=f"{tname}['{f}'] = {table[f]!r} is not "
                                f"one of {sorted(_PLANE_VALUES)}",
                    ))
        if "EngineState" in seen_classes:
            for f in reg.cross_columns:
                if reg.state_planes.get(f, "leadership") != "leadership":
                    out.append(Finding(
                        rule=self.name, path=str(reg.mod.path),
                        line=reg.lines.get("CROSS_COLUMNS", 1),
                        message=f"CROSS_COLUMNS field '{f}' must be a "
                                f"leadership plane (it holds per-peer "
                                f"state about a replica)",
                    ))
        return out


def _replace_keywords(fn: ast.AST) -> Dict[str, ast.keyword]:
    """Keyword set across every ``._replace(...)`` call in ``fn``."""
    out: Dict[str, ast.keyword] = {}
    for call in ast.walk(fn):
        if (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "_replace"
        ):
            for kw in call.keywords:
                if kw.arg is not None:
                    out[kw.arg] = kw
    return out


def _has_column_write(node: ast.AST) -> bool:
    """``x.at[g, :, p]``-style subscript: index tuple with a slice in
    position 1 — the cross-replica column axis."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Subscript):
            continue
        if not (isinstance(sub.value, ast.Attribute)
                and sub.value.attr == "at"):
            continue
        idx = sub.slice
        if (
            isinstance(idx, ast.Tuple)
            and len(idx.elts) >= 3
            and isinstance(idx.elts[1], ast.Slice)
        ):
            return True
    return False


@register
class PlaneLifecycleRule(Rule):
    name = "plane-lifecycle"
    doc = (
        "restart_replica resets exactly the volatile(+leadership) "
        "planes and never a persistent/config one; reset_replica "
        "wipes everything but the global clock and config, including "
        "the declared [g, :, p] cross-replica columns"
    )

    def check(self, project: Project) -> List[Finding]:
        reg = find_registry(project)
        if reg is None or not reg.state_planes:
            return []
        out: List[Finding] = []
        planes = reg.state_planes
        for mod in project.modules:
            for fn in ast.walk(mod.tree):
                if not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if fn.name not in ("restart_replica", "reset_replica"):
                    continue
                kws = _replace_keywords(fn)
                if not kws:
                    # Harness wrappers delegate over RPC; the
                    # tensorized lifecycle site is the _replace one.
                    continue
                if fn.name == "restart_replica":
                    out.extend(self._check_restart(mod, fn, kws, planes))
                else:
                    out.extend(self._check_reset(mod, fn, kws, reg))
        return out

    def _check_restart(
        self,
        mod: ModuleInfo,
        fn: ast.AST,
        kws: Dict[str, ast.keyword],
        planes: Dict[str, str],
    ) -> List[Finding]:
        out: List[Finding] = []
        for f, kw in kws.items():
            plane = planes.get(f)
            if plane in ("persistent", "config"):
                out.append(Finding(
                    rule=self.name, path=str(mod.path),
                    line=kw.value.lineno,
                    message=(
                        f"restart_replica resets {plane} plane '{f}' — "
                        f"a crash-restart must preserve it (raft "
                        f"readPersist discipline; reset_replica is the "
                        f"fresh-incarnation path)"
                    ),
                ))
        missing = [
            f for f, plane in planes.items()
            if plane == "volatile" and f not in kws
        ]
        for f in sorted(missing):
            out.append(Finding(
                rule=self.name, path=str(mod.path), line=fn.lineno,
                message=(
                    f"restart_replica leaves volatile plane '{f}' "
                    f"unreset — stale {f} of the dead run would survive "
                    f"the crash-restart"
                ),
            ))
        return out

    def _check_reset(
        self,
        mod: ModuleInfo,
        fn: ast.AST,
        kws: Dict[str, ast.keyword],
        reg: _Registry,
    ) -> List[Finding]:
        out: List[Finding] = []
        planes = reg.state_planes
        exempt = set(reg.global_fields) | {
            f for f, p in planes.items() if p == "config"
        }
        for f, kw in kws.items():
            if f in exempt and f in planes:
                what = ("config plane" if planes.get(f) == "config"
                        else "engine-global field")
                out.append(Finding(
                    rule=self.name, path=str(mod.path),
                    line=kw.value.lineno,
                    message=(
                        f"reset_replica touches {what} '{f}' — config "
                        f"is managed by the membership ops "
                        f"(add_learner seeds the reborn peer's view)"
                    ),
                ))
        for f in sorted(set(planes) - set(kws) - exempt):
            out.append(Finding(
                rule=self.name, path=str(mod.path), line=fn.lineno,
                message=(
                    f"reset_replica leaves plane '{f}' of the dead "
                    f"incarnation in place — a fresh incarnation must "
                    f"wipe it"
                ),
            ))
        for f in reg.cross_columns:
            kw = kws.get(f)
            if kw is None:
                continue  # the missing-wipe finding above covers it
            if not _has_column_write(kw.value):
                out.append(Finding(
                    rule=self.name, path=str(mod.path),
                    line=kw.value.lineno,
                    message=(
                        f"reset_replica clears only the own row of "
                        f"'{f}' — the [g, :, p] cross-replica column "
                        f"must be wiped too, or stale {f} about the "
                        f"reborn peer leaks into the new incarnation"
                    ),
                ))
        for f, kw in kws.items():
            if f in reg.cross_columns or f not in planes:
                continue
            if _has_column_write(kw.value):
                out.append(Finding(
                    rule=self.name, path=str(mod.path),
                    line=kw.value.lineno,
                    message=(
                        f"reset_replica wipes a [g, :, p] column of "
                        f"'{f}' that CROSS_COLUMNS does not declare — "
                        f"declare it in {reg.mod.path.name}"
                    ),
                ))
        return out
