"""graftlint rules: each encodes a bug class this codebase shipped.

Rule catalogue (names are what ``# graftlint: disable=<name>`` takes):

* ``donated-alias`` — unpickled / ``np.frombuffer`` memory reaching
  engine state through ``jnp.asarray`` without ``copy=True``.  The
  donated ``tick`` writes through the alias: CHANGES.md PR 1 shipped
  exactly this segfault in checkpoint restore.
* ``wire-width`` — a length/count packed into a fixed-width u16/u32
  wire field without a dominating bounds check.  PR 1's key-length
  bug: ``np.uint16`` silently wraps, the server reads a short key and
  the frame deserializes into garbage downstream.
* ``frame-arity`` — encoder tuple arities vs. decoder unpack/index
  arities for string-tagged RPC frames must agree (indices beyond the
  minimum encoded arity need a ``len()`` guard).  Guards against wire
  drift when a field is added to one side only.
* ``control-exempt`` — every ``add_service("X", …Control)``
  registration must have ``"X."`` in the chaos ``CONTROL_PREFIXES``
  exemption set; a control plane subject to its own chaos can
  partition away the antidote and wedge the run.
* ``jit-purity`` — no wall clocks, stdlib/numpy RNG, file I/O,
  ``print`` or ``global`` writes inside jitted / Pallas functions:
  they run at trace time only, so the op silently constant-folds (or
  worse, runs once per compile) instead of per tick.

The lock rules (``lock-order``, ``unlocked-write``) live in
``lockgraph.py``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    const_int,
    dotted_name,
    names_in,
    register,
)

# ---------------------------------------------------------------------------
# donated-alias
# ---------------------------------------------------------------------------

_TAINT_SOURCES = ("pickle.load", "pickle.loads", "frombuffer")
_STATE_CTORS = {"EngineState", "Mailbox"}
_STATE_ATTRS = {"state", "inbox"}


def _is_taint_source(call: ast.Call) -> bool:
    d = dotted_name(call.func)
    if d is None:
        return False
    return (
        d in ("pickle.load", "pickle.loads")
        or d.endswith(".frombuffer")
        or d.endswith("pickle.load")
        or d.endswith("pickle.loads")
    )


def _contains_taint_source(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call) and _is_taint_source(n)
        for n in ast.walk(node)
    )


def _target_root(node: ast.AST) -> Optional[str]:
    """Root Name of an assignment target (``host[f][g] = …`` → host)."""
    while isinstance(node, (ast.Subscript, ast.Attribute, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _has_tainted_call(node: ast.AST, call_tainted) -> bool:
    """Any Call in ``node`` that ``call_tainted`` says returns taint."""
    if call_tainted is None:
        return False
    return any(
        isinstance(n, ast.Call) and call_tainted(n)
        for n in ast.walk(node)
    )


def _tainted_names(fn: ast.AST, seeds=(), call_tainted=None) -> Set[str]:
    """Forward may-taint over a function body (statement order, two
    passes so simple forward references through loops converge).

    ``seeds`` pre-taints parameter names (interprocedural argument
    flow); ``call_tainted`` is a predicate marking calls whose return
    value is tainted (interprocedural return flow)."""
    taint: Set[str] = set(seeds)

    def expr_tainted(e: ast.AST) -> bool:
        return (
            bool(names_in(e) & taint)
            or _contains_taint_source(e)
            or _has_tainted_call(e, call_tainted)
        )

    def visit(stmts) -> None:
        for s in stmts:
            if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = s.value
                if value is None:
                    continue
                targets = (
                    s.targets if isinstance(s, ast.Assign) else [s.target]
                )
                if expr_tainted(value):
                    for t in targets:
                        if isinstance(t, ast.Tuple):
                            for el in t.elts:
                                root = _target_root(el)
                                if root:
                                    taint.add(root)
                        else:
                            root = _target_root(t)
                            if root:
                                taint.add(root)
            elif isinstance(s, ast.For):
                if expr_tainted(s.iter):
                    if isinstance(s.target, ast.Tuple):
                        for el in s.target.elts:
                            root = _target_root(el)
                            if root:
                                taint.add(root)
                    else:
                        root = _target_root(s.target)
                        if root:
                            taint.add(root)
            elif isinstance(s, ast.With):
                for item in s.items:
                    if item.optional_vars is not None and expr_tainted(
                        item.context_expr
                    ):
                        root = _target_root(item.optional_vars)
                        if root:
                            taint.add(root)
            # recurse into compound statement bodies
            for field_name in ("body", "orelse", "finalbody"):
                sub = getattr(s, field_name, None)
                if sub:
                    visit(sub)
            for handler in getattr(s, "handlers", ()):
                visit(handler.body)

    body = getattr(fn, "body", [])
    for _ in range(2):  # forward flow + one fixup pass
        visit(body)
    return taint


def _comp_taint(node: ast.AST, taint: Set[str]) -> Set[str]:
    """Comprehension targets bound from tainted iterables."""
    extra: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(
            n, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
        ):
            for gen in n.generators:
                if names_in(gen.iter) & (taint | extra) or (
                    _contains_taint_source(gen.iter)
                ):
                    if isinstance(gen.target, ast.Tuple):
                        for el in gen.target.elts:
                            root = _target_root(el)
                            if root:
                                extra.add(root)
                    else:
                        root = _target_root(gen.target)
                        if root:
                            extra.add(root)
    return extra


def _feeds_engine_state(stmt: ast.stmt) -> bool:
    """Does this statement construct or replace donated engine state?"""
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call):
            d = dotted_name(n.func)
            if d is not None:
                leaf = d.rsplit(".", 1)[-1]
                if leaf in _STATE_CTORS or leaf == "_replace":
                    return True
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        for t in targets:
            if isinstance(t, ast.Attribute) and t.attr in _STATE_ATTRS:
                return True
    return False


def _is_jnp_array_call(call: ast.Call) -> Optional[bool]:
    """True if jnp.asarray/jnp.array WITHOUT copy=True; False if the
    call defensively copies; None if not an array-construction call."""
    d = dotted_name(call.func)
    if d is None:
        return None
    if not (
        d.endswith("jnp.asarray")
        or d.endswith("jnp.array")
        or d.endswith("jax.numpy.asarray")
        or d.endswith("jax.numpy.array")
        or d in ("jnp.asarray", "jnp.array")
    ):
        return None
    for kw in call.keywords:
        if (
            kw.arg == "copy"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
        ):
            return False
    # jnp.asarray never copies when dtypes match; jnp.array without
    # copy=True defaults to copy in current jax, but the project treats
    # the explicit copy=True form as the documented safe idiom.
    if d.endswith("array") and not d.endswith("asarray"):
        # plain jnp.array(x) copies by default — accept it.
        return False
    return True


@register
class DonatedAliasRule(Rule):
    name = "donated-alias"
    doc = (
        "pickle/frombuffer-backed memory must be defensively copied "
        "(jnp.array(v, copy=True)) before it reaches donated engine "
        "state — at any call depth; the donated tick writes through "
        "zero-copy aliases."
    )

    def _fixpoint(self, project: Project):
        """Interprocedural taint: which functions RETURN tainted data,
        and which parameters RECEIVE tainted arguments.  Bounded
        rounds over the shared dataflow call graph; call-target
        resolution is cached per Call node (it dominates the cost)."""
        from .dataflow import get_dataflow, own_nodes

        df = get_dataflow(project)
        target_cache: Dict[int, list] = {}

        def targets(fi, call: ast.Call) -> list:
            key = id(call)
            if key not in target_cache:
                target_cache[key] = df.callable_targets(fi, call.func)
            return target_cache[key]

        seeds: Dict[tuple, Set[str]] = {}
        returns_tainted: Set[tuple] = set()

        # Per-function call lists and has-a-taint-source bits, computed
        # once: a function with neither (and no seeded params) cannot
        # gain or pass taint, so rounds skip it outright.
        fn_calls: Dict[tuple, list] = {}
        fn_has_source: Dict[tuple, bool] = {}
        for fi in df.funcs.values():
            calls = [
                n for n in ast.walk(fi.node) if isinstance(n, ast.Call)
            ]
            fn_calls[fi.fid] = calls
            fn_has_source[fi.fid] = any(
                _is_taint_source(c) for c in calls
            )

        for _ in range(8):
            changed = False
            for fi in df.funcs.values():
                def call_tainted(c: ast.Call, fi=fi) -> bool:
                    return any(
                        t.fid in returns_tainted for t in targets(fi, c)
                    )

                if not (
                    seeds.get(fi.fid)
                    or fn_has_source[fi.fid]
                    or any(call_tainted(c) for c in fn_calls[fi.fid])
                ):
                    continue
                taint = _tainted_names(
                    fi.node, seeds.get(fi.fid, ()), call_tainted
                )
                for n in own_nodes(fi.node):
                    if (
                        isinstance(n, ast.Return)
                        and n.value is not None
                        and fi.fid not in returns_tainted
                        and (
                            names_in(n.value) & taint
                            or _contains_taint_source(n.value)
                            or _has_tainted_call(n.value, call_tainted)
                        )
                    ):
                        returns_tainted.add(fi.fid)
                        changed = True
                    if not isinstance(n, ast.Call):
                        continue
                    tgts = targets(fi, n)
                    if not tgts:
                        continue
                    for pos, arg in enumerate(n.args):
                        if not (
                            names_in(arg) & taint
                            or _contains_taint_source(arg)
                            or _has_tainted_call(arg, call_tainted)
                        ):
                            continue
                        for t in tgts:
                            params = [a.arg for a in t.node.args.args]
                            # Bound-method call through an attribute:
                            # positional args land after self/cls.
                            off = (
                                1
                                if params[:1] in (["self"], ["cls"])
                                and isinstance(n.func, ast.Attribute)
                                else 0
                            )
                            idx = pos + off
                            if idx < len(params):
                                s = seeds.setdefault(t.fid, set())
                                if params[idx] not in s:
                                    s.add(params[idx])
                                    changed = True
                    for kw in n.keywords:
                        if kw.arg is None or not (
                            names_in(kw.value) & taint
                            or _contains_taint_source(kw.value)
                            or _has_tainted_call(kw.value, call_tainted)
                        ):
                            continue
                        for t in tgts:
                            params = {a.arg for a in t.node.args.args}
                            if kw.arg in params:
                                s = seeds.setdefault(t.fid, set())
                                if kw.arg not in s:
                                    s.add(kw.arg)
                                    changed = True
            if not changed:
                break
        return df, seeds, returns_tainted, targets, fn_calls, \
            fn_has_source

    def check(self, project: Project) -> List[Finding]:
        (df, seeds, returns_tainted, targets, fn_calls,
         fn_has_source) = self._fixpoint(project)
        out: List[Finding] = []
        for fi in df.funcs.values():
            fn = fi.node

            def call_tainted(c: ast.Call, fi=fi) -> bool:
                return any(
                    t.fid in returns_tainted for t in targets(fi, c)
                )

            if not (
                seeds.get(fi.fid)
                or fn_has_source[fi.fid]
                or any(call_tainted(c) for c in fn_calls[fi.fid])
            ):
                continue
            taint = _tainted_names(
                fn, seeds.get(fi.fid, ()), call_tainted
            )
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.stmt):
                    continue
                if not _feeds_engine_state(stmt):
                    continue
                local = taint | _comp_taint(stmt, taint)
                for call in ast.walk(stmt):
                    if not isinstance(call, ast.Call):
                        continue
                    if _is_jnp_array_call(call) is not True:
                        continue
                    if not call.args:
                        continue
                    arg = call.args[0]
                    if (
                        names_in(arg) & local
                        or _contains_taint_source(arg)
                        or _has_tainted_call(arg, call_tainted)
                    ):
                        out.append(
                            Finding(
                                rule=self.name,
                                path=str(fi.path),
                                line=call.lineno,
                                message=(
                                    "value derived from pickle/"
                                    "frombuffer reaches engine state "
                                    "via jnp.asarray without "
                                    "copy=True; the donated tick "
                                    "writes through the aliased host "
                                    "buffer (use jnp.array(v, "
                                    "copy=True))"
                                ),
                            )
                        )
        # Nested defs are visited both as their own FuncInfo and via
        # the enclosing function's statement walk — keep one finding.
        seen: Set[Tuple[str, int]] = set()
        unique: List[Finding] = []
        for f in out:
            if (f.path, f.line) not in seen:
                seen.add((f.path, f.line))
                unique.append(f)
        return unique


# ---------------------------------------------------------------------------
# wire-width
# ---------------------------------------------------------------------------

_LEN_NAME = re.compile(r"(^|_)(n|len|count|num|rows?)($|_)|_len$|^len")
_GUARD_NAME = re.compile(r"^MAX_|_MAX$|LIMIT|^CAP_|_CAP$")
_U16_BOUNDS = {2**16, 2**16 - 1}
_U32_BOUNDS = {2**32, 2**32 - 1}
_U16_DTYPES = {"<u2", "u2", ">u2", "uint16"}
_U32_DTYPES = {"<u4", "u4", ">u4", "uint32"}


def _is_len_like(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            d = dotted_name(n.func)
            if d == "len":
                return True
        if isinstance(n, ast.Name) and _LEN_NAME.search(n.id):
            return True
    return False


def _module_dtype_widths(tree: ast.Module) -> Dict[str, int]:
    """Module-level ``_U16 = np.dtype("<u2")`` style aliases → width."""
    widths: Dict[str, int] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        tgt = stmt.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        v = stmt.value
        if (
            isinstance(v, ast.Call)
            and dotted_name(v.func) is not None
            and dotted_name(v.func).endswith("dtype")
            and v.args
            and isinstance(v.args[0], ast.Constant)
        ):
            spec = str(v.args[0].value)
            if spec in _U16_DTYPES:
                widths[tgt.id] = 16
            elif spec in _U32_DTYPES:
                widths[tgt.id] = 32
    return widths


def _struct_formats(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``X = struct.Struct("<fmt")`` aliases → format."""
    fmts: Dict[str, str] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        tgt = stmt.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        v = stmt.value
        if (
            isinstance(v, ast.Call)
            and dotted_name(v.func) in ("struct.Struct", "Struct")
            and v.args
            and isinstance(v.args[0], ast.Constant)
        ):
            fmts[tgt.id] = str(v.args[0].value)
    return fmts


def _fmt_arg_types(fmt: str) -> List[str]:
    """Struct format → one type char per packed argument."""
    out: List[str] = []
    count = ""
    for ch in fmt:
        if ch in "<>=!@ ":
            continue
        if ch.isdigit():
            count += ch
            continue
        n = int(count) if count else 1
        count = ""
        if ch == "s":
            out.append("s")  # one bytes arg regardless of count
        elif ch == "x":
            continue
        else:
            out.extend(ch * n)
    return out


def _dtype_arg_width(
    node: ast.AST, aliases: Dict[str, int]
) -> Optional[int]:
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    d = dotted_name(node)
    if d is not None:
        if d.endswith("uint16"):
            return 16
        if d.endswith("uint32"):
            return 32
    if (
        isinstance(node, ast.Call)
        and dotted_name(node.func) is not None
        and dotted_name(node.func).endswith("dtype")
        and node.args
        and isinstance(node.args[0], ast.Constant)
    ):
        spec = str(node.args[0].value)
        if spec in _U16_DTYPES:
            return 16
        if spec in _U32_DTYPES:
            return 32
    return None


def _has_width_guard(fn: ast.AST, width: int) -> bool:
    bounds = _U16_BOUNDS if width == 16 else _U32_BOUNDS
    for n in ast.walk(fn):
        if isinstance(n, ast.Compare):
            operands = [n.left, *n.comparators]
            for op in operands:
                c = const_int(op)
                if c is not None and c in bounds:
                    return True
                if isinstance(op, ast.Name) and _GUARD_NAME.search(op.id):
                    return True
                d = dotted_name(op)
                if d is not None and _GUARD_NAME.search(
                    d.rsplit(".", 1)[-1]
                ):
                    return True
    return False


@register
class WireWidthRule(Rule):
    name = "wire-width"
    doc = (
        "a length/count cast to u16/u32 for the wire must be dominated "
        "by a bounds check in the same function; fixed-width casts "
        "silently wrap."
    )

    def check(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            aliases = _module_dtype_widths(mod.tree)
            fmts = _struct_formats(mod.tree)
            for fn in ast.walk(mod.tree):
                if not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    for width, expr, line in self._sinks(
                        call, aliases, fmts
                    ):
                        if not _is_len_like(expr):
                            continue
                        if _has_width_guard(fn, width):
                            continue
                        out.append(
                            Finding(
                                rule=self.name,
                                path=str(mod.path),
                                line=line,
                                message=(
                                    f"length/count packed as u{width} "
                                    "without a bounds check in this "
                                    "function; the cast wraps silently "
                                    f"past 2**{width} (guard with an "
                                    "explicit limit and raise)"
                                ),
                            )
                        )
        return out

    def _sinks(
        self,
        call: ast.Call,
        aliases: Dict[str, int],
        fmts: Dict[str, str],
    ):
        """Yield (width, packed_expr, line) for fixed-width pack sites."""
        d = dotted_name(call.func)
        if d is None:
            return
        # np.uint16(x) / np.uint32(x)
        if d.endswith("uint16") and call.args:
            yield 16, call.args[0], call.lineno
        elif d.endswith("uint32") and call.args:
            yield 32, call.args[0], call.lineno
        # np.asarray(x, dtype) / np.array(x, dtype)
        elif d.endswith("asarray") or d.endswith(".array"):
            dtype_node = None
            if len(call.args) >= 2:
                dtype_node = call.args[1]
            for kw in call.keywords:
                if kw.arg == "dtype":
                    dtype_node = kw.value
            if dtype_node is not None and call.args:
                w = _dtype_arg_width(dtype_node, aliases)
                if w is not None:
                    yield w, call.args[0], call.lineno
        # struct.pack("fmt", ...) and StructAlias.pack(...)
        elif d.endswith(".pack") or d == "pack":
            fmt = None
            args = call.args
            if d in ("struct.pack", "pack") and args:
                if isinstance(args[0], ast.Constant):
                    fmt = str(args[0].value)
                    args = args[1:]
            else:
                base = d.rsplit(".", 1)[0]
                fmt = fmts.get(base)
            if fmt is None:
                return
            types = _fmt_arg_types(fmt)
            for ch, arg in zip(types, args):
                if ch == "H":
                    yield 16, arg, call.lineno
                elif ch in ("I", "L"):
                    yield 32, arg, call.lineno


# ---------------------------------------------------------------------------
# frame-arity
# ---------------------------------------------------------------------------


def _tag_of_test(test: ast.AST) -> Optional[Tuple[str, str]]:
    """``name[0] == "tag"`` → (name, tag)."""
    for n in ast.walk(test):
        if not isinstance(n, ast.Compare):
            continue
        if len(n.ops) != 1 or not isinstance(n.ops[0], ast.Eq):
            continue
        left, right = n.left, n.comparators[0]
        for sub, const in ((left, right), (right, left)):
            if (
                isinstance(sub, ast.Subscript)
                and isinstance(sub.value, ast.Name)
                and isinstance(sub.slice, ast.Constant)
                and sub.slice.value == 0
                and isinstance(const, ast.Constant)
                and isinstance(const.value, str)
            ):
                return sub.value.id, const.value
    return None


def _branch_has_len_guard(branch_nodes, name: str) -> bool:
    for root in branch_nodes:
        for n in ast.walk(root):
            if isinstance(n, ast.Compare):
                for op in [n.left, *n.comparators]:
                    if (
                        isinstance(op, ast.Call)
                        and dotted_name(op.func) == "len"
                        and op.args
                        and isinstance(op.args[0], ast.Name)
                        and op.args[0].id == name
                    ):
                        return True
    return False


@register
class FrameArityRule(Rule):
    name = "frame-arity"
    doc = (
        "string-tagged wire tuples: decoder index/unpack arities must "
        "agree with every encoder arity for the same tag (extra fields "
        "need a len() guard)."
    )

    def check(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            arities = self._encode_arities(mod)
            if not arities:
                continue
            for branch in self._decode_branches(mod):
                name, tag, test, body, line = branch
                if tag not in arities:
                    continue
                lo = min(arities[tag])
                guarded = _branch_has_len_guard([test, *body], name)
                for node in body:
                    for n in ast.walk(node):
                        if (
                            isinstance(n, ast.Subscript)
                            and isinstance(n.value, ast.Name)
                            and n.value.id == name
                            and isinstance(n.slice, ast.Constant)
                            and isinstance(n.slice.value, int)
                            and n.slice.value >= lo
                            and not guarded
                        ):
                            out.append(
                                Finding(
                                    rule=self.name,
                                    path=str(mod.path),
                                    line=n.lineno,
                                    message=(
                                        f'decoder reads {name}[{n.slice.value}] '
                                        f'for tag "{tag}" but the encoder '
                                        f"produces arities {sorted(arities[tag])}; "
                                        "guard the access with len()"
                                    ),
                                )
                            )
                        if (
                            isinstance(n, ast.Assign)
                            and len(n.targets) == 1
                            and isinstance(n.targets[0], ast.Tuple)
                            and isinstance(n.value, ast.Name)
                            and n.value.id == name
                        ):
                            k = len(n.targets[0].elts)
                            if k not in arities[tag]:
                                out.append(
                                    Finding(
                                        rule=self.name,
                                        path=str(mod.path),
                                        line=n.lineno,
                                        message=(
                                            f"decoder unpacks {k} fields "
                                            f'for tag "{tag}" but the '
                                            "encoder produces arities "
                                            f"{sorted(arities[tag])}"
                                        ),
                                    )
                                )
        return out

    def _encode_arities(self, mod: ModuleInfo) -> Dict[str, Set[int]]:
        arities: Dict[str, Set[int]] = {}
        for n in ast.walk(mod.tree):
            if (
                isinstance(n, ast.Tuple)
                and n.elts
                and isinstance(n.elts[0], ast.Constant)
                and isinstance(n.elts[0].value, str)
            ):
                arities.setdefault(n.elts[0].value, set()).add(len(n.elts))
        return arities

    def _decode_branches(self, mod: ModuleInfo):
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.If):
                hit = _tag_of_test(n.test)
                if hit:
                    yield (*hit, n.test, n.body, n.lineno)
            elif isinstance(n, ast.IfExp):
                hit = _tag_of_test(n.test)
                if hit:
                    yield (*hit, n.test, [n.body], n.lineno)


# ---------------------------------------------------------------------------
# control-exempt
# ---------------------------------------------------------------------------


@register
class ControlExemptRule(Rule):
    name = "control-exempt"
    doc = (
        "every add_service registration of a *Control service must "
        "appear in CONTROL_PREFIXES, or chaos can partition away its "
        "own control plane."
    )

    def check(self, project: Project) -> List[Finding]:
        prefixes = self._prefixes(project)
        if prefixes is None:
            return []
        out: List[Finding] = []
        for mod in project.modules:
            for fn_or_mod in [mod.tree, *ast.walk(mod.tree)]:
                if not isinstance(
                    fn_or_mod,
                    (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef),
                ):
                    continue
                # local name → True if assigned from a *Control() call
                control_vars: Set[str] = set()
                for n in ast.walk(fn_or_mod):
                    if isinstance(n, ast.Assign) and self._is_control_ctor(
                        n.value
                    ):
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                control_vars.add(t.id)
                for n in ast.walk(fn_or_mod):
                    if not (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "add_service"
                        and len(n.args) >= 2
                        and isinstance(n.args[0], ast.Constant)
                        and isinstance(n.args[0].value, str)
                    ):
                        continue
                    svc = n.args[0].value
                    obj = n.args[1]
                    is_control = self._is_control_ctor(obj) or (
                        isinstance(obj, ast.Name) and obj.id in control_vars
                    )
                    if is_control and f"{svc}." not in prefixes:
                        out.append(
                            Finding(
                                rule=self.name,
                                path=str(mod.path),
                                line=n.lineno,
                                message=(
                                    f'control service "{svc}" is not in '
                                    "CONTROL_PREFIXES "
                                    f"{sorted(prefixes)}; its RPCs are "
                                    "subject to chaos and cannot heal a "
                                    "partitioned fleet"
                                ),
                            )
                        )
        return out

    @staticmethod
    def _is_control_ctor(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d = dotted_name(node.func)
        return d is not None and d.rsplit(".", 1)[-1].endswith("Control")

    @staticmethod
    def _prefixes(project: Project) -> Optional[Set[str]]:
        found: Optional[Set[str]] = None
        for mod in project.modules:
            for stmt in mod.tree.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "CONTROL_PREFIXES"
                    and isinstance(stmt.value, (ast.Tuple, ast.List, ast.Set))
                ):
                    vals = {
                        e.value
                        for e in stmt.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    }
                    found = (found or set()) | vals
        return found


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

_IMPURE_CALLS = {
    "time.time",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.monotonic",
    "time.sleep",
}


def _jit_wrapped_names(tree: ast.Module) -> Set[str]:
    """Names of functions compiled by jax.jit / pallas_call in a module."""

    def collect_fn_names(node: ast.AST, acc: Set[str]) -> None:
        """Function names referenced inside a jit(...) argument list,
        through partial()/shard_map() wrappers."""
        if isinstance(node, ast.Name):
            acc.add(node.id)
        elif isinstance(node, ast.Call):
            for a in node.args:
                collect_fn_names(a, acc)

    def is_jit_expr(node: ast.AST) -> bool:
        d = dotted_name(node)
        if d is not None and (d.endswith("jax.jit") or d == "jit"):
            return True
        # functools.partial(jax.jit, ...)
        if isinstance(node, ast.Call):
            fd = dotted_name(node.func)
            if fd is not None and fd.endswith("partial") and node.args:
                return is_jit_expr(node.args[0])
        return False

    jitted: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in n.decorator_list:
                if is_jit_expr(dec) or (
                    isinstance(dec, ast.Call) and is_jit_expr(dec.func)
                ):
                    jitted.add(n.name)
        if isinstance(n, ast.Call):
            d = dotted_name(n.func)
            # jax.jit(f, ...) / jax.jit(shard_map(f, ...))
            if is_jit_expr(n.func) and n.args:
                collect_fn_names(n.args[0], jitted)
            # functools.partial(jax.jit, ...)(f)
            elif (
                isinstance(n.func, ast.Call)
                and is_jit_expr(n.func)
                and n.args
            ):
                collect_fn_names(n.args[0], jitted)
            # pl.pallas_call(kernel, ...)
            elif d is not None and d.endswith("pallas_call") and n.args:
                collect_fn_names(n.args[0], jitted)
    return jitted


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    doc = (
        "jitted/Pallas functions run at trace time: wall clocks, "
        "stdlib RNG, I/O and global writes silently constant-fold "
        "into the compiled graph."
    )

    def check(self, project: Project) -> List[Finding]:
        from .dataflow import get_dataflow

        df = get_dataflow(project)
        out: List[Finding] = []
        for mod in project.modules:
            jitted = _jit_wrapped_names(mod.tree)
            if not jitted:
                continue
            for fn in ast.walk(mod.tree):
                if (
                    isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name in jitted
                ):
                    out.extend(self._scan(mod, fn))
                    out.extend(self._scan_callees(df, mod, fn, jitted))
        return out

    @staticmethod
    def _impurity_of(n: ast.AST) -> Optional[str]:
        """Description of the impurity a node performs, or None."""
        if isinstance(n, ast.Call):
            d = dotted_name(n.func)
            if d is None:
                return None
            if d in _IMPURE_CALLS:
                return f"wall-clock call {d}()"
            if d.startswith("random.") or d.startswith(
                ("np.random.", "numpy.random.")
            ):
                return f"host RNG call {d}()"
            if d == "open":
                return "file I/O (open)"
            if d == "print":
                return "print()"
            return None
        if isinstance(n, ast.Global):
            return f"global write ({', '.join(n.names)})"
        return None

    def _scan(self, mod: ModuleInfo, fn: ast.AST) -> List[Finding]:
        out: List[Finding] = []
        for n in ast.walk(fn):
            what = self._impurity_of(n)
            if what is not None:
                out.append(
                    Finding(
                        rule=self.name,
                        path=str(mod.path),
                        line=n.lineno,
                        message=(
                            f"{what} inside jitted function "
                            f"'{getattr(fn, 'name', '?')}' executes at "
                            "trace time only (constant-folds into the "
                            "compiled graph)"
                        ),
                    )
                )
        return out

    def _scan_callees(
        self,
        df: "object",
        mod: ModuleInfo,
        fn: ast.AST,
        jitted: Set[str],
    ) -> List[Finding]:
        """One-level closure: impurities inside project helpers the
        jitted function calls, flagged at the call site.  Jitted
        callees are skipped — they are scanned (and flagged) on their
        own."""
        from .dataflow import own_nodes

        fi = df.func_of_node(fn)  # type: ignore[attr-defined]
        if fi is None:
            return []
        out: List[Finding] = []
        for call in own_nodes(fn):
            if not isinstance(call, ast.Call):
                continue
            for tgt in df.resolve_call(fi, call):  # type: ignore[attr-defined]
                if tgt.name in jitted and tgt.path == str(mod.path):
                    continue
                what = next(
                    (
                        w
                        for n in own_nodes(tgt.node)
                        if (w := self._impurity_of(n)) is not None
                    ),
                    None,
                )
                if what is not None:
                    out.append(
                        Finding(
                            rule=self.name,
                            path=str(mod.path),
                            line=call.lineno,
                            message=(
                                f"call to '{tgt.name}' from jitted "
                                f"function '{getattr(fn, 'name', '?')}' "
                                f"reaches {what} — it executes at trace "
                                "time only (constant-folds into the "
                                "compiled graph)"
                            ),
                        )
                    )
        return out
