"""graftlint v3: registry-drift rules.

The deployment plane keeps several hand-maintained registries whose
consumers live in other files: the flight recorder's numbered event
types (decoded by the postmortem doctor), the chaos kind vocabulary
(flightrec codes ⇄ nemesis verbs ⇄ ``make_schedule`` include sets),
the hello wire-capability strings (negotiated at scattered membership
tests), and the ``MRT_*`` env-knob table (``utils/knobs.py``).  Each
rule here makes the registry and its consumers drift-proof:

* ``record-codes`` — every ``_TYPE_NAMES`` key resolves to a unique
  integer constant, every recorded type constant is in the table, and
  every type is referenced by the postmortem doctor's decoders.
* ``chaos-kinds`` — literal kinds at ``_hit``/``note_fault`` sites
  must be ``CHAOS_KIND_CODES`` keys; every window kind emitted by
  ``make_schedule`` must be handled by a nemesis verb comparison; the
  ``include`` default set must be kinds ``make_schedule`` dispatches.
* ``wire-caps`` — capability strings tested against a ``caps``
  variable must be declared in ``_WIRE_CAPS``, and every declared cap
  must be negotiated (tested) somewhere.
* ``env-knob`` — a raw ``os.environ`` read of an ``MRT_*`` literal
  outside the knobs module is a finding (use the typed accessors), and
  a ``knob_*()`` accessor call with an undeclared name is a finding.

Approximations (ARCHITECTURE §11): registries are recognized by their
literal shapes (``_TYPE_NAMES`` dicts keyed by Names, ``KNOBS`` tuples
of ``Knob(...)`` calls, ``_WIRE_CAPS`` string tuples); dynamic kinds
(``note_fault(path, kind)`` forwarding a variable) and env names built
at runtime are out of scope; the doctor-coverage and untested-cap
arms need both sides present in the linted project, so single-file
fixtures exercise them via fixture directories.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    const_int,
    dotted_name,
    register,
)

_UPPER = re.compile(r"^[A-Z][A-Z0-9_]*$")


def _top_assign(mod: ModuleInfo, name: str) -> Optional[ast.stmt]:
    for stmt in mod.tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and stmt.targets[0].id == name
        ):
            return stmt
    return None


def _int_consts(mod: ModuleInfo) -> Dict[str, Tuple[int, int]]:
    """Top-level ``NAME = <int>`` bindings → (value, line)."""
    out: Dict[str, Tuple[int, int]] = {}
    for stmt in mod.tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
        ):
            v = const_int(stmt.value)
            if v is not None:
                out[stmt.targets[0].id] = (v, stmt.lineno)
    return out


def _leaf(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


# ---------------------------------------------------------------------------
# record-codes
# ---------------------------------------------------------------------------


@register
class RecordCodesRule(Rule):
    name = "record-codes"
    doc = (
        "flight-record type codes must be unique, registered in "
        "_TYPE_NAMES, and known to the postmortem doctor's decoders"
    )

    def check(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            stmt = _top_assign(mod, "_TYPE_NAMES")
            if stmt is None or not isinstance(stmt.value, ast.Dict):
                continue
            out.extend(self._check_table(project, mod, stmt))
        return out

    def _check_table(
        self, project: Project, mod: ModuleInfo, stmt: ast.stmt
    ) -> List[Finding]:
        out: List[Finding] = []
        consts = _int_consts(mod)
        keys: List[str] = []
        for k in stmt.value.keys:  # type: ignore[union-attr]
            kn = _leaf(k)
            if kn is None:
                continue
            keys.append(kn)
            if kn not in consts:
                out.append(Finding(
                    rule=self.name, path=str(mod.path), line=k.lineno,
                    message=f"_TYPE_NAMES key {kn} resolves to no "
                            f"module-level integer constant",
                ))
        # Uniqueness among the registered type codes.
        by_value: Dict[int, List[str]] = {}
        for kn in keys:
            if kn in consts:
                by_value.setdefault(consts[kn][0], []).append(kn)
        for value, names in sorted(by_value.items()):
            if len(names) > 1:
                for kn in names[1:]:
                    out.append(Finding(
                        rule=self.name, path=str(mod.path),
                        line=consts[kn][1],
                        message=(
                            f"flight-record type code {value} collides: "
                            f"{names[0]} and {kn} share it — readers "
                            f"cannot tell the events apart"
                        ),
                    ))
        # Every recorded constant of this module must be registered.
        known = set(keys)
        for m2 in project.modules:
            for call in ast.walk(m2.tree):
                if not (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr == "record"
                    and call.args
                ):
                    continue
                leaf = _leaf(call.args[0])
                if (
                    leaf is not None
                    and _UPPER.match(leaf)
                    and leaf in consts
                    and leaf not in known
                ):
                    out.append(Finding(
                        rule=self.name, path=str(m2.path),
                        line=call.lineno,
                        message=(
                            f"recorded event type {leaf} is not in "
                            f"_TYPE_NAMES — readers will print a bare "
                            f"number for it"
                        ),
                    ))
        # Doctor coverage: every registered type must be referenced by
        # a postmortem module's decoders.
        doctors = project.find("postmortem")
        if doctors:
            referenced: Set[str] = set()
            for d in doctors:
                for n in ast.walk(d.tree):
                    leaf = _leaf(n)
                    if leaf is not None:
                        referenced.add(leaf)
            for kn in keys:
                if kn in consts and kn not in referenced:
                    out.append(Finding(
                        rule=self.name, path=str(mod.path),
                        line=consts[kn][1],
                        message=(
                            f"flight-record type {kn} is unknown to the "
                            f"postmortem doctor — no decoder references "
                            f"it, so its events vanish from reports"
                        ),
                    ))
        return out


# ---------------------------------------------------------------------------
# chaos-kinds
# ---------------------------------------------------------------------------


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@register
class ChaosKindsRule(Rule):
    name = "chaos-kinds"
    doc = (
        "chaos kind literals at _hit/note_fault sites must be "
        "CHAOS_KIND_CODES keys; make_schedule's emitted window kinds "
        "and include defaults must match the nemesis verbs"
    )

    def check(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        codes: Optional[Set[str]] = None
        for mod in project.modules:
            stmt = _top_assign(mod, "CHAOS_KIND_CODES")
            if stmt is not None and isinstance(stmt.value, ast.Dict):
                codes = {
                    s for s in (_str_const(k) for k in stmt.value.keys)
                    if s is not None
                }
                break
        if codes:
            out.extend(self._check_hit_sites(project, codes))
        out.extend(self._check_schedule(project))
        return out

    def _check_hit_sites(
        self, project: Project, codes: Set[str]
    ) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            for call in ast.walk(mod.tree):
                if not (
                    isinstance(call, ast.Call)
                    and _leaf(call.func) in ("_hit", "note_fault")
                    and len(call.args) >= 2
                ):
                    continue
                kind = _str_const(call.args[1])
                if kind is not None and kind not in codes:
                    out.append(Finding(
                        rule=self.name, path=str(mod.path),
                        line=call.lineno,
                        message=(
                            f"chaos kind '{kind}' has no "
                            f"CHAOS_KIND_CODES entry — its flight-"
                            f"record events carry code 0 and the "
                            f"doctor cannot attribute them"
                        ),
                    ))
        return out

    def _handled_kinds(self, project: Project) -> Set[str]:
        """String kinds some nemesis class compares ``kind`` against
        (``kind == "x"`` / ``kind in (...)``) — collected from classes
        defining a ``_start`` dispatcher."""
        handled: Set[str] = set()
        for mod in project.modules:
            for cls in ast.walk(mod.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                if not any(
                    isinstance(n, ast.FunctionDef) and n.name == "_start"
                    for n in cls.body
                ):
                    continue
                for cmp_ in ast.walk(cls):
                    if not isinstance(cmp_, ast.Compare):
                        continue
                    if not (
                        isinstance(cmp_.left, ast.Name)
                        and cmp_.left.id == "kind"
                    ):
                        continue
                    for op, comp in zip(cmp_.ops, cmp_.comparators):
                        if isinstance(op, (ast.Eq, ast.NotEq)):
                            s = _str_const(comp)
                            if s is not None:
                                handled.add(s)
                        elif isinstance(op, (ast.In, ast.NotIn)):
                            if isinstance(comp, (ast.Tuple, ast.List,
                                                 ast.Set)):
                                for el in comp.elts:
                                    s = _str_const(el)
                                    if s is not None:
                                        handled.add(s)
        return handled

    def _check_schedule(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        handled = self._handled_kinds(project)
        for mod in project.modules:
            for fn in ast.walk(mod.tree):
                if not (
                    isinstance(fn, ast.FunctionDef)
                    and fn.name == "make_schedule"
                ):
                    continue
                # Kinds the if-chain dispatches (`kind == "x"`).
                dispatched: Set[str] = set()
                for cmp_ in ast.walk(fn):
                    if (
                        isinstance(cmp_, ast.Compare)
                        and isinstance(cmp_.left, ast.Name)
                        and cmp_.left.id == "kind"
                        and len(cmp_.ops) == 1
                        and isinstance(cmp_.ops[0], ast.Eq)
                    ):
                        s = _str_const(cmp_.comparators[0])
                        if s is not None:
                            dispatched.add(s)
                # include default set ⊆ dispatched kinds.
                args = fn.args
                defaults = dict(
                    zip([a.arg for a in args.args][-len(args.defaults):],
                        args.defaults)
                ) if args.defaults else {}
                inc = defaults.get("include")
                if dispatched and isinstance(inc, (ast.Tuple, ast.List)):
                    for el in inc.elts:
                        s = _str_const(el)
                        if s is not None and s not in dispatched:
                            out.append(Finding(
                                rule=self.name, path=str(mod.path),
                                line=el.lineno,
                                message=(
                                    f"include default '{s}' is not a "
                                    f"kind make_schedule dispatches — "
                                    f"schedules would raise on it"
                                ),
                            ))
                # Emitted window kinds ⊆ nemesis-handled verbs.
                if not handled:
                    continue
                for call in ast.walk(fn):
                    if not (
                        isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr == "append"
                        and len(call.args) == 1
                        and isinstance(call.args[0], ast.Tuple)
                        and len(call.args[0].elts) >= 2
                    ):
                        continue
                    s = _str_const(call.args[0].elts[1])
                    if s is not None and s not in handled:
                        out.append(Finding(
                            rule=self.name, path=str(mod.path),
                            line=call.lineno,
                            message=(
                                f"make_schedule emits window kind "
                                f"'{s}' that no nemesis verb handles "
                                f"(_start would raise mid-run)"
                            ),
                        ))
        return out


# ---------------------------------------------------------------------------
# wire-caps
# ---------------------------------------------------------------------------


@register
class WireCapsRule(Rule):
    name = "wire-caps"
    doc = (
        "hello capability strings tested against a caps set must be "
        "declared in _WIRE_CAPS, and every declared cap must be "
        "negotiated somewhere"
    )

    def check(self, project: Project) -> List[Finding]:
        decl: Optional[Tuple[ModuleInfo, ast.stmt, Set[str]]] = None
        for mod in project.modules:
            stmt = _top_assign(mod, "_WIRE_CAPS")
            if stmt is not None and isinstance(
                stmt.value, (ast.Tuple, ast.List)
            ):
                caps = {
                    s for s in (_str_const(el) for el in stmt.value.elts)
                    if s is not None
                }
                decl = (mod, stmt, caps)
                break
        if decl is None:
            return []
        dmod, dstmt, caps = decl
        out: List[Finding] = []
        tested: Set[str] = set()
        for mod in project.modules:
            for cmp_ in ast.walk(mod.tree):
                if not (
                    isinstance(cmp_, ast.Compare)
                    and len(cmp_.ops) == 1
                    and isinstance(cmp_.ops[0], (ast.In, ast.NotIn))
                ):
                    continue
                s = _str_const(cmp_.left)
                if s is None:
                    continue
                leaf = _leaf(cmp_.comparators[0])
                if leaf is None or "cap" not in leaf.lower():
                    continue
                tested.add(s)
                if s not in caps:
                    out.append(Finding(
                        rule=self.name, path=str(mod.path),
                        line=cmp_.lineno,
                        message=(
                            f"capability '{s}' is tested against the "
                            f"negotiated caps but not declared in "
                            f"_WIRE_CAPS — this build never offers it, "
                            f"so the branch is dead (or the hello "
                            f"payload drifted)"
                        ),
                    ))
        for s in sorted(caps - tested):
            out.append(Finding(
                rule=self.name, path=str(dmod.path), line=dstmt.lineno,
                message=(
                    f"_WIRE_CAPS declares '{s}' but no site tests for "
                    f"it — the capability is advertised and never "
                    f"negotiated"
                ),
            ))
        return out


# ---------------------------------------------------------------------------
# env-knob
# ---------------------------------------------------------------------------

_ACCESSORS = ("knob_str", "knob_int", "knob_float", "knob_bool")


def _knob_decls(project: Project) -> Tuple[Set[str], Set[str]]:
    """(declared knob names, paths of modules defining KNOBS)."""
    names: Set[str] = set()
    paths: Set[str] = set()
    for mod in project.modules:
        stmt = _top_assign(mod, "KNOBS")
        if stmt is None or not isinstance(stmt.value, (ast.Tuple, ast.List)):
            continue
        found = False
        for el in stmt.value.elts:
            if not (isinstance(el, ast.Call) and _leaf(el.func) == "Knob"):
                continue
            name = None
            if el.args:
                name = _str_const(el.args[0])
            for kw in el.keywords:
                if kw.arg == "name":
                    name = _str_const(kw.value)
            if name is not None:
                names.add(name)
                found = True
        if found:
            paths.add(str(mod.path))
    return names, paths


@register
class EnvKnobRule(Rule):
    name = "env-knob"
    doc = (
        "MRT_* environment knobs must be declared in utils/knobs.py "
        "and read through the typed accessors — raw os.environ reads "
        "and undeclared accessor names are findings"
    )

    def check(self, project: Project) -> List[Finding]:
        declared, knob_paths = _knob_decls(project)
        out: List[Finding] = []
        for mod in project.modules:
            in_registry = str(mod.path) in knob_paths
            for node in ast.walk(mod.tree):
                if not in_registry:
                    raw = self._raw_read(node)
                    if raw is not None:
                        name, line = raw
                        out.append(Finding(
                            rule=self.name, path=str(mod.path), line=line,
                            message=(
                                f"raw os.environ read of '{name}' — "
                                f"declare it in utils/knobs.py KNOBS "
                                f"and use the typed knob_*() accessor"
                            ),
                        ))
                        continue
                if declared and isinstance(node, ast.Call):
                    leaf = _leaf(node.func)
                    if leaf in _ACCESSORS and node.args:
                        name = _str_const(node.args[0])
                        if name is not None and name not in declared:
                            out.append(Finding(
                                rule=self.name, path=str(mod.path),
                                line=node.lineno,
                                message=(
                                    f"knob accessor reads '{name}' "
                                    f"which KNOBS does not declare — "
                                    f"add the registry entry (type, "
                                    f"default, doc)"
                                ),
                            ))
        return out

    @staticmethod
    def _raw_read(node: ast.AST) -> Optional[Tuple[str, int]]:
        """(MRT name, line) when ``node`` reads os.environ raw."""
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d is not None and (
                d.endswith("environ.get") or d.endswith("getenv")
            ):
                if node.args:
                    s = _str_const(node.args[0])
                    if s is not None and s.startswith("MRT_"):
                        return s, node.lineno
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            d = dotted_name(node.value)
            if d is not None and d.endswith("environ"):
                s = _str_const(node.slice)
                if s is not None and s.startswith("MRT_"):
                    return s, node.lineno
        elif isinstance(node, ast.Compare) and len(node.ops) == 1:
            if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                d = dotted_name(node.comparators[0])
                if d is not None and d.endswith("environ"):
                    s = _str_const(node.left)
                    if s is not None and s.startswith("MRT_"):
                        return s, node.lineno
        return None
