"""graftlint dataflow: whole-project call graph + serving-path rules.

PR 3's rules are intraprocedural — each looks at one function (or one
module) at a time.  The bug classes the serving push courts (ROADMAP
items 2-3) are not: a queue grows in ``_reply`` because a *callback
registration* three calls away put it on the scheduler loop, and a
checkpoint fsync blocks the loop because a pump tick reached it through
two layers of durability plumbing.  This module builds the shared
interprocedural substrate once per lint run:

* **Function table** — every def (methods, nested defs included) as a
  :class:`FuncInfo` keyed by ``(path, qualname)``.
* **Class table** — :class:`ClassInfo` with lock attributes and
  one-step ctor-param attribute typing (grown out of lockgraph.py's
  collector, which now consumes this table instead of building its
  own).
* **Call resolution** — ``self.meth`` / ``self.a.b.meth`` chains via
  attribute types, module functions, imported project functions,
  nested defs, local aliases (``reply = self._reply if … else …``),
  ctor-typed locals (``fut = Future(); fut.resolve``).
* **Serving roots** — the functions that run on a scheduler loop
  thread or as RPC handlers: callables registered through
  ``call_at/call_after/call_soon/post/spawn/run_call/
  add_done_callback``, ``*Scheduler(...)`` ctor hooks (io_poll /
  io_handle / io_flush), and the public methods of every class passed
  to ``add_service``.
* **Reachability** — BFS over the call graph from those roots; the
  serving-path rules below only fire inside the reachable set.

Approximations (deliberate, documented): one type per attribute /
local (last ctor wins), no flow through containers or ``**kwargs``,
dynamic dispatch through reassigned bound-method attributes is
invisible, and a callback registered in dead code still roots its
target.  All three rules err toward silence outside the resolved
serving set and toward noise inside it — the pragma machinery from
core.py is the escape hatch, and every suppression is inventoried by
``-v`` / the test suite.

Rules that live here:

* ``unbounded-queue`` — a ``self.<attr>`` container that grows
  (``append``/``appendleft``/``add``, incl. ``setdefault(...).append``
  chains and local aliases of the attribute) inside a serving-reachable
  function, with no dominating bound check (a ``len()`` comparison
  mentioning the container) or shed path (``pop``/``popleft``/
  ``clear``/``discard``/``del``/truncating re-slice) in the same
  function.  The seed true positive was tcp.py's per-connection reply
  queue (fixed in this PR with a cap + shed-oldest policy).
* ``blocking-in-callback`` — ``time.sleep``, ``os.fsync``/
  ``os.fdatasync``, blocking socket ``sendall``, ``run_call``
  rendezvous, ``sched.wait`` and blocking ``lock.acquire()`` reached
  from a scheduler/timer callback: each one stalls the single loop
  thread that every reply on this node rides on.  The WAL/disk
  durability layer is allowlisted (its contract IS sync-on-pump);
  everything else needs an explicit pragma.
* ``wire-schema`` — frame-arity extended across modules: tuple frames
  that actually flow into ``codec.encode`` / ``codec.encode_oob``
  (both the 0x80 legacy pickle path and the 0x01 out-of-band path,
  including the coalesced ``repb`` reply frames) are collected
  project-wide and checked against every decoder branch, wherever it
  lives.  Same-module drift stays frame-arity's report (no double
  findings).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, Project, Rule, dotted_name, register

__all__ = [
    "ClassInfo",
    "Dataflow",
    "FuncInfo",
    "get_dataflow",
    "is_lock_ctor",
    "own_nodes",
]

FuncId = Tuple[str, str]  # (path, qualname)

_LOCK_CTORS = ("Lock", "RLock", "Condition")


def is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted_name(node.func)
    if d is None:
        return False
    return d.rsplit(".", 1)[-1] in _LOCK_CTORS


@dataclass
class ClassInfo:
    """One class: its methods, lock attributes, and attribute types
    (``self.x = T(...)`` plus one-step ctor-param binding)."""

    name: str
    module: str
    path: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    lock_attrs: Set[str] = field(default_factory=set)
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)


@dataclass
class FuncInfo:
    """One def — top-level, method, or nested — with enough context to
    resolve ``self`` and enclosing-scope names."""

    path: str
    module: str  # file stem
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls: Optional[str] = None  # nearest enclosing class (self's type)
    parent: Optional["FuncInfo"] = None  # nearest enclosing function

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def fid(self) -> FuncId:
        return (self.path, self.qualname)


def own_nodes(root: ast.AST) -> Iterator[ast.AST]:
    """Nodes in a function's own body, NOT descending into nested defs
    or lambdas — their bodies execute later, in their own frame, and
    are analyzed as their own functions (lambdas at their registration
    site)."""
    stack: List[ast.AST] = list(getattr(root, "body", []))
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(
                c, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(c)


def _attr_chain(expr: ast.AST) -> Optional[Tuple[str, List[str]]]:
    """``a.b.c`` → ``("a", ["b", "c"])``; None unless rooted at a Name."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id, list(reversed(parts))
    return None


# Callback-registering method name → index of the callable argument.
_CB_ATTRS = {
    "call_at": 1,
    "call_after": 1,
    "call_soon": 0,
    "post": 0,
    "spawn": 0,
    "run_call": 0,
    "add_done_callback": 0,
}


class Dataflow:
    """The shared interprocedural substrate for one :class:`Project`.

    Build once via :func:`get_dataflow` (memoized on the project);
    lockgraph.py and the serving-path rules all read from the same
    instance, so collection cost is paid once per lint run.
    """

    def __init__(self, project: Project) -> None:
        self.project = project
        self.classes: Dict[str, ClassInfo] = {}
        # stem-keyed views kept for the lock-graph rules (which collapse
        # same-stem modules exactly as before this refactor).
        self.module_locks: Dict[str, Set[str]] = {}
        self.module_funcs: Dict[str, Dict[str, ast.FunctionDef]] = {}
        self.funcs: Dict[FuncId, FuncInfo] = {}
        self._stems: Set[str] = {m.name for m in project.modules}
        self._stem_path: Dict[str, str] = {}
        self._toplevel: Dict[str, Dict[str, FuncInfo]] = {}
        self._methods: Dict[Tuple[str, str], FuncInfo] = {}
        self._nested: Dict[Tuple[FuncId, str], FuncInfo] = {}
        self._by_node: Dict[int, FuncInfo] = {}
        # alias → ("mod", stem) | ("from", "stem:name"), per file
        self._imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self._assign_memo: Dict[FuncId, Dict[str, List[ast.AST]]] = {}
        self._edges: Optional[Dict[FuncId, Set[FuncId]]] = None
        self._reach: Optional[Dict[FuncId, Tuple[str, str]]] = None
        self._collect()
        self._bind_ctor_params()

    # -- collection --------------------------------------------------------

    def _collect(self) -> None:
        for mod in self.project.modules:
            stem, path = mod.name, str(mod.path)
            self._stem_path.setdefault(stem, path)
            self.module_funcs.setdefault(stem, {})
            self.module_locks.setdefault(stem, set())
            self._toplevel[path] = {}
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and is_lock_ctor(stmt.value):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks[stem].add(t.id)
            self._imports[path] = self._scan_imports(mod.tree)
            self._visit(stem, path, mod.tree, cls=None, parent=None, prefix="")

    def _visit(
        self,
        stem: str,
        path: str,
        node: ast.AST,
        cls: Optional[ClassInfo],
        parent: Optional[FuncInfo],
        prefix: str,
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                ci = self._make_class(stem, path, child)
                self._visit(
                    stem, path, child, cls=ci, parent=None,
                    prefix=prefix + child.name + ".",
                )
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + child.name
                fi = FuncInfo(
                    path=path, module=stem, qualname=qual, node=child,
                    cls=cls.name if cls is not None else None,
                    parent=parent,
                )
                self.funcs[fi.fid] = fi
                self._by_node[id(child)] = fi
                if parent is not None:
                    self._nested[(parent.fid, child.name)] = fi
                elif cls is not None:
                    self._methods.setdefault((cls.name, child.name), fi)
                else:
                    self._toplevel[path].setdefault(child.name, fi)
                    self.module_funcs[stem].setdefault(child.name, child)
                self._visit(
                    stem, path, child, cls=cls, parent=fi,
                    prefix=qual + ".",
                )
            else:
                self._visit(stem, path, child, cls, parent, prefix)

    def _make_class(
        self, stem: str, path: str, node: ast.ClassDef
    ) -> ClassInfo:
        ci = ClassInfo(
            name=node.name,
            module=stem,
            path=path,
            node=node,
            bases=[
                b.rsplit(".", 1)[-1]
                for b in (dotted_name(base) for base in node.bases)
                if b is not None
            ],
        )
        for item in node.body:
            if isinstance(item, ast.FunctionDef):
                ci.methods[item.name] = item
        for meth in ci.methods.values():
            for n in ast.walk(meth):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Attribute)
                    and isinstance(n.targets[0].value, ast.Name)
                    and n.targets[0].value.id == "self"
                ):
                    attr = n.targets[0].attr
                    if is_lock_ctor(n.value):
                        ci.lock_attrs.add(attr)
                    else:
                        t = self._ctor_class(n.value)
                        if t is not None:
                            ci.attr_types[attr] = t
        self.classes.setdefault(node.name, ci)
        return self.classes[node.name]

    @staticmethod
    def _ctor_class(value: ast.AST) -> Optional[str]:
        """Class name constructed anywhere in an assignment RHS."""
        for n in ast.walk(value):
            if isinstance(n, ast.Call):
                d = dotted_name(n.func)
                if d is not None:
                    leaf = d.rsplit(".", 1)[-1]
                    if leaf[:1].isupper():
                        return leaf
        return None

    def _scan_imports(self, tree: ast.Module) -> Dict[str, Tuple[str, str]]:
        imp: Dict[str, Tuple[str, str]] = {}
        for n in ast.walk(tree):
            if isinstance(n, ast.Import):
                for a in n.names:
                    leaf = a.name.split(".")[-1]
                    if leaf in self._stems:
                        imp[a.asname or leaf] = ("mod", leaf)
            elif isinstance(n, ast.ImportFrom):
                modleaf = (n.module or "").split(".")[-1]
                for a in n.names:
                    if a.name in self._stems:
                        imp[a.asname or a.name] = ("mod", a.name)
                    elif modleaf in self._stems:
                        imp[a.asname or a.name] = (
                            "from", f"{modleaf}:{a.name}"
                        )
        return imp

    def _bind_ctor_params(self) -> None:
        """One-step inter-procedural attr typing: wherever ``T(x, …)``
        is called with a typable argument, bind T.__init__'s parameter
        to that type, so ``self._dur = dur`` inside T.__init__ types
        ``_dur``.  This closes back-references (transport → node) and
        dependency injection through serve()-style builders."""
        for _ in range(2):  # fixpoint over 1-hop chains
            for fi in self.funcs.values():
                for call in own_nodes(fi.node):
                    if not isinstance(call, ast.Call):
                        continue
                    d = dotted_name(call.func)
                    if d is None:
                        continue
                    target = self.classes.get(d.rsplit(".", 1)[-1])
                    if target is None or "__init__" not in target.methods:
                        continue
                    params = [
                        a.arg
                        for a in target.methods["__init__"].args.args
                    ][1:]  # drop self
                    bound: Dict[str, str] = {}
                    for p, arg in zip(params, call.args):
                        t = self._class_of_expr(fi, arg, 3)
                        if t is not None:
                            bound[p] = t
                    for kw in call.keywords:
                        if kw.arg is not None:
                            t = self._class_of_expr(fi, kw.value, 3)
                            if t is not None:
                                bound[kw.arg] = t
                    if not bound:
                        continue
                    for n in ast.walk(target.methods["__init__"]):
                        if (
                            isinstance(n, ast.Assign)
                            and len(n.targets) == 1
                            and isinstance(n.targets[0], ast.Attribute)
                            and isinstance(n.targets[0].value, ast.Name)
                            and n.targets[0].value.id == "self"
                            and isinstance(n.value, ast.Name)
                            and n.value.id in bound
                        ):
                            target.attr_types.setdefault(
                                n.targets[0].attr, bound[n.value.id]
                            )

    # -- name/type resolution ----------------------------------------------

    def toplevel_func(self, stem: str, name: str) -> Optional[FuncInfo]:
        path = self._stem_path.get(stem)
        if path is None:
            return None
        return self._toplevel.get(path, {}).get(name)

    def lookup_method(
        self, cls_name: str, meth: str, _seen: Optional[Set[str]] = None
    ) -> Optional[FuncInfo]:
        hit = self._methods.get((cls_name, meth))
        if hit is not None:
            return hit
        ci = self.classes.get(cls_name)
        if ci is None:
            return None
        seen = _seen or set()
        if cls_name in seen:
            return None
        seen.add(cls_name)
        for b in ci.bases:
            hit = self.lookup_method(b, meth, seen)
            if hit is not None:
                return hit
        return None

    def resolve_attr_class(
        self, cls_name: str, chain: Sequence[str]
    ) -> Optional[str]:
        """Type of ``self.a.b`` given self's class and ["a", "b"]."""
        cur: Optional[str] = cls_name
        for a in chain:
            ci = self.classes.get(cur or "")
            cur = ci.attr_types.get(a) if ci is not None else None
            if cur is None:
                return None
        return cur

    def _local_assigns(self, fi: FuncInfo) -> Dict[str, List[ast.AST]]:
        memo = self._assign_memo.get(fi.fid)
        if memo is None:
            memo = {}
            for n in own_nodes(fi.node):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                ):
                    memo.setdefault(n.targets[0].id, []).append(n.value)
            self._assign_memo[fi.fid] = memo
        return memo

    def _local_type(
        self, fi: FuncInfo, name: str, depth: int
    ) -> Optional[str]:
        """Class of a local: ``x = Cls(...)``, ``x = sched.run_call(
        build)`` (build's return class), ``x = make()`` (make's return
        class)."""
        if depth <= 0:
            return None
        if name == "self":
            return fi.cls
        p: Optional[FuncInfo] = fi
        while p is not None:
            for rhs in self._local_assigns(p).get(name, ()):
                t = self._class_of_expr(p, rhs, depth - 1)
                if t is not None:
                    return t
            p = p.parent
        return None

    def _class_of_expr(
        self, fi: Optional[FuncInfo], expr: ast.AST, depth: int
    ) -> Optional[str]:
        if depth <= 0:
            return None
        if isinstance(expr, ast.IfExp):
            return self._class_of_expr(
                fi, expr.body, depth - 1
            ) or self._class_of_expr(fi, expr.orelse, depth - 1)
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fi is not None:
                return fi.cls
            if expr.id in self.classes:
                return None  # a class object, not an instance
            if fi is not None:
                return self._local_type(fi, expr.id, depth)
            return None
        if isinstance(expr, ast.Attribute):
            bc = _attr_chain(expr)
            if bc is None or fi is None:
                return None
            base, chain = bc
            if base == "self" and fi.cls:
                return self.resolve_attr_class(fi.cls, chain)
            t = self._local_type(fi, base, depth - 1)
            if t is not None:
                return self.resolve_attr_class(t, chain)
            return None
        if isinstance(expr, ast.Call):
            d = dotted_name(expr.func)
            leaf = d.rsplit(".", 1)[-1] if d is not None else None
            if leaf is not None and leaf in self.classes:
                return leaf
            # sched.run_call(build, ...) — the loop-thread constructor
            # rendezvous: the result is whatever ``build`` returns.
            if leaf == "run_call" and expr.args:
                for t in self.callable_targets(fi, expr.args[0], depth - 1):
                    rc = self._return_class(t, depth - 1)
                    if rc is not None:
                        return rc
                return None
            for t in self.callable_targets(fi, expr.func, depth - 1):
                rc = self._return_class(t, depth - 1)
                if rc is not None:
                    return rc
        return None

    def _return_class(self, fi: FuncInfo, depth: int) -> Optional[str]:
        if fi.name == "__init__" and fi.cls:
            return fi.cls
        for n in own_nodes(fi.node):
            if isinstance(n, ast.Return) and n.value is not None:
                t = self._class_of_expr(fi, n.value, depth)
                if t is not None:
                    return t
        return None

    def callable_targets(
        self, fi: Optional[FuncInfo], expr: ast.AST, depth: int = 4
    ) -> List[FuncInfo]:
        """Project functions a callable expression may denote.  Handles
        bound methods (through typed attribute chains), module and
        imported functions, nested defs, local aliases (including
        conditional ``a if c else b``), lambdas (their call targets),
        and ctor references (→ ``__init__``)."""
        if depth <= 0:
            return []
        out: List[FuncInfo] = []
        if isinstance(expr, ast.IfExp):
            return self.callable_targets(
                fi, expr.body, depth - 1
            ) + self.callable_targets(fi, expr.orelse, depth - 1)
        if isinstance(expr, ast.Lambda):
            for n in ast.walk(expr.body):
                if isinstance(n, ast.Call):
                    out.extend(
                        self.callable_targets(fi, n.func, depth - 1)
                    )
            return out
        if isinstance(expr, ast.Call):
            # A callback built by a call: spawn(_guarded(gen)),
            # partial(fn, ...).  Collect from callee and arguments.
            out.extend(self.callable_targets(fi, expr.func, depth - 1))
            for a in expr.args:
                out.extend(self.callable_targets(fi, a, depth - 1))
            return out
        if isinstance(expr, ast.Name):
            name = expr.id
            p = fi
            while p is not None:
                hit = self._nested.get((p.fid, name))
                if hit is not None:
                    return [hit]
                p = p.parent
            p = fi
            while p is not None:
                for rhs in self._local_assigns(p).get(name, ()):
                    out.extend(self.callable_targets(p, rhs, depth - 1))
                p = p.parent
            if out:
                return out
            if fi is not None:
                hit = self._toplevel.get(fi.path, {}).get(name)
                if hit is not None:
                    return [hit]
                imp = self._imports.get(fi.path, {}).get(name)
                if imp is not None and imp[0] == "from":
                    stem, fname = imp[1].split(":", 1)
                    tl = self.toplevel_func(stem, fname)
                    if tl is not None:
                        return [tl]
            if name in self.classes:
                init = self.lookup_method(name, "__init__")
                return [init] if init is not None else []
            return []
        if isinstance(expr, ast.Attribute):
            bc = _attr_chain(expr)
            if bc is None:
                return []
            base, chain = bc
            meth, mid = chain[-1], chain[:-1]
            if base == "self" and fi is not None and fi.cls:
                owner: Optional[str] = fi.cls
                if mid:
                    owner = self.resolve_attr_class(fi.cls, mid)
                if owner:
                    hit = self.lookup_method(owner, meth)
                    return [hit] if hit is not None else []
                return []
            if fi is not None and not mid:
                imp = self._imports.get(fi.path, {}).get(base)
                if imp is not None and imp[0] == "mod":
                    hit = self.toplevel_func(imp[1], meth)
                    return [hit] if hit is not None else []
            if base in self.classes and not mid:
                hit = self.lookup_method(base, meth)
                return [hit] if hit is not None else []
            if fi is not None:
                t = self._local_type(fi, base, depth - 1)
                if t is not None:
                    owner = self.resolve_attr_class(t, mid) if mid else t
                    if owner:
                        hit = self.lookup_method(owner, meth)
                        return [hit] if hit is not None else []
            return []
        return []

    def resolve_call(self, fi: FuncInfo, call: ast.Call) -> List[FuncInfo]:
        return self.callable_targets(fi, call.func)

    def func_of_node(self, node: ast.AST) -> Optional[FuncInfo]:
        return self._by_node.get(id(node))

    # -- call graph / roots / reachability ---------------------------------

    def call_edges(self) -> Dict[FuncId, Set[FuncId]]:
        if self._edges is None:
            edges: Dict[FuncId, Set[FuncId]] = {}
            for fi in self.funcs.values():
                tgts: Set[FuncId] = set()
                for n in own_nodes(fi.node):
                    if isinstance(n, ast.Call):
                        for t in self.callable_targets(fi, n.func):
                            tgts.add(t.fid)
                edges[fi.fid] = tgts
            self._edges = edges
        return self._edges

    def serving_roots(self) -> Dict[FuncId, Tuple[str, str]]:
        """fid → (kind, label) for every function that enters the
        serving path: scheduler/timer callbacks and RPC handlers."""
        roots: Dict[FuncId, Tuple[str, str]] = {}

        def add(t: FuncInfo, kind: str, label: str) -> None:
            roots.setdefault(t.fid, (kind, label))

        contexts: List[Tuple[Optional[FuncInfo], ast.AST]] = [
            (fi, fi.node) for fi in self.funcs.values()
        ]
        # module top-level statements (serve() blocks, script mains)
        for mod in self.project.modules:
            contexts.append((None, mod.tree))
        for fi, body in contexts:
            for n in own_nodes(body):
                if not isinstance(n, ast.Call):
                    continue
                d = dotted_name(n.func)
                leaf = d.rsplit(".", 1)[-1] if d is not None else None
                # SomeScheduler(...) ctor: every callable argument is an
                # io/timer hook that runs on the loop thread.
                if (
                    leaf is not None
                    and leaf.endswith("Scheduler")
                    and leaf in self.classes
                ):
                    hook_args = list(n.args) + [
                        kw.value for kw in n.keywords
                    ]
                    for a in hook_args:
                        for t in self.callable_targets(fi, a):
                            add(t, "callback", f"{leaf}() hook")
                    continue
                if not isinstance(n.func, ast.Attribute):
                    continue
                attr = n.func.attr
                if attr in _CB_ATTRS:
                    idx = _CB_ATTRS[attr]
                    if len(n.args) > idx:
                        where = fi.qualname if fi is not None else "<module>"
                        for t in self.callable_targets(fi, n.args[idx]):
                            add(t, "callback", f"{attr} in {where}")
                elif attr == "add_service":
                    self._service_roots(fi, n, add)
        return roots

    def _service_roots(self, fi, call: ast.Call, add) -> None:
        svc, obj = None, None
        if (
            len(call.args) >= 2
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
        ):
            svc, obj = call.args[0].value, call.args[1]
        elif len(call.args) == 1:
            # sim shape: add_service(Service(obj, name="Raft"))
            a = call.args[0]
            if isinstance(a, ast.Call) and a.args:
                d = dotted_name(a.func)
                if d is not None and d.rsplit(".", 1)[-1] == "Service":
                    obj = a.args[0]
                    for kw in a.keywords:
                        if (
                            kw.arg == "name"
                            and isinstance(kw.value, ast.Constant)
                        ):
                            svc = str(kw.value.value)
        if obj is None:
            return
        cls = self._class_of_expr(fi, obj, 4)
        ci = self.classes.get(cls or "")
        if ci is None:
            return
        label = f'rpc "{svc or ci.name}"'
        for mname in ci.methods:
            if mname.startswith("_"):
                continue
            m = self.lookup_method(ci.name, mname)
            if m is not None:
                add(m, "rpc", label)

    def reachable(self) -> Dict[FuncId, Tuple[str, str]]:
        """fid → (kind, root label) for every function reachable from a
        serving root over the resolved call graph."""
        if self._reach is None:
            edges = self.call_edges()
            reach: Dict[FuncId, Tuple[str, str]] = {}
            queue: List[FuncId] = []
            for fid, info in self.serving_roots().items():
                if fid not in reach:
                    reach[fid] = info
                    queue.append(fid)
            while queue:
                cur = queue.pop()
                info = reach[cur]
                for nxt in edges.get(cur, ()):
                    if nxt not in reach:
                        reach[nxt] = info
                        queue.append(nxt)
            self._reach = reach
        return self._reach


def get_dataflow(project: Project) -> Dataflow:
    """The memoized per-project :class:`Dataflow` (built on first use;
    all rules in one ``run()`` share it)."""
    df = getattr(project, "_graftlint_dataflow", None)
    if df is None:
        df = Dataflow(project)
        project._graftlint_dataflow = df  # type: ignore[attr-defined]
    return df


# ---------------------------------------------------------------------------
# unbounded-queue
# ---------------------------------------------------------------------------

_GROW_ATTRS = {"append", "appendleft", "add"}
_SHED_ATTRS = {"pop", "popleft", "popitem", "clear", "discard", "remove"}


def _container_attr(expr: ast.AST) -> Optional[str]:
    """The self-attribute behind a growing receiver: ``self.X``,
    ``self.X[k]``, ``self.X.setdefault(...)``, ``self.X.get(...)``."""
    if isinstance(expr, ast.Attribute):
        cur: ast.AST = expr
        while isinstance(cur, ast.Attribute):
            cur = cur.value
        if isinstance(cur, ast.Name) and cur.id == "self":
            return expr.attr
        return None
    if isinstance(expr, ast.Subscript):
        return _container_attr(expr.value)
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr in ("setdefault", "get")
    ):
        return _container_attr(expr.func.value)
    return None


def _mentions_container(node: ast.AST, attr: str, aliases: Set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == attr:
            return True
        if isinstance(n, ast.Name) and n.id in aliases:
            return True
    return False


def _has_bound_or_shed(
    nodes: List[ast.AST], attr: str, aliases: Set[str]
) -> bool:
    """A dominating bound check (len() comparison mentioning the
    container) or shed path (pop/clear/del/truncating re-slice) in the
    same function."""
    for n in nodes:
        if isinstance(n, ast.Compare):
            for side in [n.left, *n.comparators]:
                for c in ast.walk(side):
                    if (
                        isinstance(c, ast.Call)
                        and dotted_name(c.func) == "len"
                        and c.args
                        and _mentions_container(c.args[0], attr, aliases)
                    ):
                        return True
        elif (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in _SHED_ATTRS
            and _mentions_container(n.func.value, attr, aliases)
        ):
            return True
        elif isinstance(n, ast.Delete):
            if any(
                _mentions_container(t, attr, aliases) for t in n.targets
            ):
                return True
        elif isinstance(n, ast.Assign):
            # truncation: self.X = self.X[-k:] (or alias re-slice)
            if any(
                _mentions_container(t, attr, aliases) for t in n.targets
            ) and any(
                isinstance(c, ast.Subscript)
                and _mentions_container(c.value, attr, aliases)
                for c in ast.walk(n.value)
            ):
                return True
    return False


@register
class UnboundedQueueRule(Rule):
    name = "unbounded-queue"
    doc = (
        "a self-attribute container growing inside a serving-reachable "
        "function needs a dominating bound check or shed path in that "
        "function: an overloaded server must shed, not grow until the "
        "flight recorder is the only witness."
    )

    def check(self, project: Project) -> List[Finding]:
        df = get_dataflow(project)
        out: List[Finding] = []
        for fid, (kind, root) in df.reachable().items():
            fi = df.funcs[fid]
            nodes = list(own_nodes(fi.node))
            # include enclosing-function context for guards: a nested
            # callback may rely on a bound its parent establishes
            guard_nodes = list(nodes)
            p = fi.parent
            while p is not None:
                guard_nodes.extend(own_nodes(p.node))
                p = p.parent
            aliases: Dict[str, str] = {}
            for n in nodes:
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                ):
                    a = _container_attr(n.value)
                    if a is not None:
                        aliases[n.targets[0].id] = a
            for n in nodes:
                if not (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _GROW_ATTRS
                ):
                    continue
                recv = n.func.value
                attr = _container_attr(recv)
                if attr is None and isinstance(recv, ast.Name):
                    attr = aliases.get(recv.id)
                if attr is None:
                    continue
                # self.wal.append(...) where wal is a project class
                # DEFINING append: not a container — the growth (if
                # any) is inside that method, analyzed there.
                if fi.cls:
                    t = df.resolve_attr_class(fi.cls, [attr])
                    if t and df.lookup_method(t, n.func.attr):
                        continue
                names = {k for k, v in aliases.items() if v == attr}
                if _has_bound_or_shed(guard_nodes, attr, names):
                    continue
                out.append(
                    Finding(
                        rule=self.name,
                        path=fi.path,
                        line=n.lineno,
                        message=(
                            f"self.{attr} grows in {fi.qualname} on the "
                            f"serving path (reachable from {kind} root "
                            f"{root}) with no bound check or shed path "
                            "in this function; an overload grows it "
                            "without limit (cap it and shed, or "
                            "suppress with a comment saying what bounds "
                            "it)"
                        ),
                    )
                )
        return out


# ---------------------------------------------------------------------------
# blocking-in-callback
# ---------------------------------------------------------------------------

# The durability layer's whole contract is sync-on-pump (group commit):
# its fsyncs are the product, not a stall bug.  engine_pump is the
# engine pipeline's dedicated device-wait thread: blocking there is the
# design — it exists precisely so the scheduler loop never blocks on a
# readback (distributed/engine_pump.py).
_BLOCK_ALLOW_MODULES = {"wal", "disk", "engine_pump"}


def _blocking_what(call: ast.Call) -> Optional[str]:
    d = dotted_name(call.func)
    f = call.func
    if d is not None and (d == "time.sleep" or d.endswith(".time.sleep")):
        return "time.sleep()"
    leaf: Optional[str]
    if isinstance(f, ast.Attribute):
        leaf = f.attr
    elif isinstance(f, ast.Name):
        leaf = f.id
    else:
        return None
    if leaf in ("fsync", "fdatasync"):
        return f"os.{leaf}()"
    if leaf == "sendall":
        return "blocking socket sendall()"
    if leaf == "run_call":
        return "run_call() cross-thread rendezvous"
    if isinstance(f, ast.Attribute):
        recv = dotted_name(f.value) or ""
        low = recv.lower()
        if leaf == "acquire" and (
            "lock" in low or "cond" in low or low.endswith("cv")
        ):
            for kw in call.keywords:
                if (
                    kw.arg == "blocking"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                ):
                    return None
            if call.args and (
                isinstance(call.args[0], ast.Constant)
                and call.args[0].value is False
            ):
                return None
            return f"blocking {recv}.acquire()"
        if leaf == "wait" and (low == "sched" or low.endswith(".sched")):
            return f"{recv}.wait() (the loop waiting on itself deadlocks)"
    return None


@register
class BlockingInCallbackRule(Rule):
    name = "blocking-in-callback"
    doc = (
        "fsync / time.sleep / blocking sends / lock-acquire / "
        "run_call reached from a scheduler or timer callback stall the "
        "single loop thread every reply rides on (WAL/disk sync points "
        "are allowlisted; anything else needs an explicit pragma)."
    )

    def check(self, project: Project) -> List[Finding]:
        df = get_dataflow(project)
        out: List[Finding] = []
        for fid, (kind, root) in df.reachable().items():
            fi = df.funcs[fid]
            if fi.module in _BLOCK_ALLOW_MODULES:
                continue
            for n in own_nodes(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                what = _blocking_what(n)
                if what is None:
                    continue
                out.append(
                    Finding(
                        rule=self.name,
                        path=fi.path,
                        line=n.lineno,
                        message=(
                            f"{what} in {fi.qualname} runs on the "
                            f"scheduler loop thread (reachable from "
                            f"{kind} root {root}); it stalls every "
                            "reply on this node while it blocks"
                        ),
                    )
                )
        return out


# ---------------------------------------------------------------------------
# wire-schema
# ---------------------------------------------------------------------------


def _is_codec_sink(call: ast.Call) -> bool:
    d = dotted_name(call.func)
    if d is None:
        return False
    parts = d.split(".")
    return (
        parts[-1] in ("encode", "encode_oob")
        and len(parts) >= 2
        and parts[-2] == "codec"
    )


@register
class WireSchemaRule(Rule):
    name = "wire-schema"
    doc = (
        "string-tagged frames that flow into codec.encode/encode_oob "
        "(legacy 0x80 and out-of-band 0x01 paths alike) are collected "
        "project-wide; every decoder branch must agree with every "
        "encoder arity for the tag, across module boundaries "
        "(same-module drift stays frame-arity's report)."
    )

    def check(self, project: Project) -> List[Finding]:
        from .rules import _branch_has_len_guard

        df = get_dataflow(project)
        edges = df.call_edges()
        # Functions in an encoding context: call a codec sink directly,
        # or call (one level) a project function that does.
        direct: Set[FuncId] = set()
        for fi in df.funcs.values():
            for n in own_nodes(fi.node):
                if isinstance(n, ast.Call) and _is_codec_sink(n):
                    direct.add(fi.fid)
                    break
        contexts = set(direct)
        for fid, tgts in edges.items():
            if tgts & direct:
                contexts.add(fid)
        # tag → {arity}, and tag → {path} for the cross-module filter.
        wire_ar: Dict[str, Set[int]] = {}
        wire_paths: Dict[str, Set[str]] = {}
        for fid in contexts:
            fi = df.funcs[fid]
            for n in own_nodes(fi.node):
                for t in ast.walk(n):
                    if (
                        isinstance(t, ast.Tuple)
                        and t.elts
                        and isinstance(t.elts[0], ast.Constant)
                        and isinstance(t.elts[0].value, str)
                    ):
                        tag = t.elts[0].value
                        wire_ar.setdefault(tag, set()).add(len(t.elts))
                        wire_paths.setdefault(tag, set()).add(fi.path)
        if not wire_ar:
            return []
        out: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        for mod in project.modules:
            path = str(mod.path)
            own = self._lexical_arities(mod.tree)
            for branch in self._decode_branches(mod.tree):
                name, tag, test, body, _line = branch
                arities = wire_ar.get(tag)
                if not arities:
                    continue
                if not (wire_paths.get(tag, set()) - {path}):
                    continue  # no cross-module encoder: frame-arity turf
                lo = min(arities)
                own_ar = own.get(tag, set())
                guarded = _branch_has_len_guard([test, *body], name)
                for node in body:
                    for n in ast.walk(node):
                        if (
                            isinstance(n, ast.Subscript)
                            and isinstance(n.value, ast.Name)
                            and n.value.id == name
                            and isinstance(n.slice, ast.Constant)
                            and isinstance(n.slice.value, int)
                            and n.slice.value >= lo
                            and not guarded
                        ):
                            if own_ar and n.slice.value >= min(own_ar):
                                continue  # frame-arity reports this one
                            key = (path, n.lineno)
                            if key in seen:
                                continue
                            seen.add(key)
                            out.append(
                                Finding(
                                    rule=self.name,
                                    path=path,
                                    line=n.lineno,
                                    message=(
                                        f"decoder reads {name}"
                                        f"[{n.slice.value}] for tag "
                                        f'"{tag}" but cross-module '
                                        "encoders ship arities "
                                        f"{sorted(arities)} into "
                                        "codec.encode/encode_oob; guard "
                                        "the access with len()"
                                    ),
                                )
                            )
                        if (
                            isinstance(n, ast.Assign)
                            and len(n.targets) == 1
                            and isinstance(n.targets[0], ast.Tuple)
                            and isinstance(n.value, ast.Name)
                            and n.value.id == name
                        ):
                            k = len(n.targets[0].elts)
                            if k in arities:
                                continue
                            if own_ar and k not in own_ar:
                                continue  # frame-arity reports this one
                            key = (path, n.lineno)
                            if key in seen:
                                continue
                            seen.add(key)
                            out.append(
                                Finding(
                                    rule=self.name,
                                    path=path,
                                    line=n.lineno,
                                    message=(
                                        f"decoder unpacks {k} fields "
                                        f'for tag "{tag}" but '
                                        "cross-module encoders ship "
                                        f"arities {sorted(arities)} "
                                        "into codec.encode/encode_oob"
                                    ),
                                )
                            )
        return out

    @staticmethod
    def _lexical_arities(tree: ast.Module) -> Dict[str, Set[int]]:
        arities: Dict[str, Set[int]] = {}
        for n in ast.walk(tree):
            if (
                isinstance(n, ast.Tuple)
                and n.elts
                and isinstance(n.elts[0], ast.Constant)
                and isinstance(n.elts[0].value, str)
            ):
                arities.setdefault(n.elts[0].value, set()).add(len(n.elts))
        return arities

    @staticmethod
    def _decode_branches(tree: ast.Module):
        from .rules import _tag_of_test

        for n in ast.walk(tree):
            if isinstance(n, ast.If):
                hit = _tag_of_test(n.test)
                if hit:
                    yield (*hit, n.test, n.body, n.lineno)
            elif isinstance(n, ast.IfExp):
                hit = _tag_of_test(n.test)
                if hit:
                    yield (*hit, n.test, [n.body], n.lineno)
