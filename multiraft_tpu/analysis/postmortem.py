"""Postmortem doctor: read the fleet's black boxes and say what broke.

``python -m multiraft_tpu.analysis.postmortem <bundle>`` consumes a
bundle directory produced by :func:`multiraft_tpu.harness.bundle.
collect_bundle` (flight rings + final snapshots + manifest) and emits:

* a human-readable report (stdout + ``<bundle>/report.txt``): per
  process — clean vs UNCLEAN death, last committed op (group / client /
  command / rid), WAL fsync gap (appends that were staged but never
  fsync'd when the process died), last known role/term/commit per raft
  peer, chaos fault bursts; fleet-wide — a clock-aligned anomaly
  timeline with the FIRST anomaly called out, and commit/apply lag
  from the final ``Obs.groups`` snapshots.
* a Perfetto trace (``<bundle>/flight_trace.json.gz``): every intact
  ring record as a span/instant/counter on one clock-aligned time
  axis, commit instants tagged with their rid so a request can be
  chased across processes with the trace viewer's search.

Clock alignment reuses the harness's min-RTT offsets: the manifest
maps address → offset (remote perf_counter µs − host) and address →
pid, so each ring's timestamps shift by −offset onto the host clock —
including rings of processes that were dead at collection time, whose
offsets were cached while they lived.

The doctor also accepts a bare ``.ring`` file or a directory of rings
(no manifest): alignment degrades to per-process clocks, the analyses
still run.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from ..distributed import flightrec
from ..utils.knobs import knob_float, knob_int
from ..utils.trace import Tracer

__all__ = ["load_bundle", "analyze", "build_report", "main"]

Record = Dict[str, Any]

# A reply-drop (or any chaos) burst this dense is worth a report line:
# ≥ BURST_MIN faults inside BURST_WINDOW_US.
BURST_WINDOW_US = 1_000_000.0
BURST_MIN = 5

# Placement thrash: the same group moved ≥ THRASH_MIN times inside
# THRASH_WINDOW_US (PLACE records, placement.py controller).  A healthy
# controller's cooldown/hysteresis keeps any one group far below this;
# hitting it means the planner is oscillating.
THRASH_WINDOW_US = 30_000_000.0
THRASH_MIN = 3

# Data-loss window for shipped state (stateplane.py): an UNCLEAN death
# whose last shipment for some group is older than this at the ring's
# end means writes inside the window died unshipped.  Resolved from the
# same env knob the shipper uses, so doctor and plane agree.
def _ship_window_us() -> float:
    return knob_float("MRT_SHIP_WINDOW_S") * 1e6


# Degraded-quorum bound for membership changes (placement.py healer):
# a reconfig open longer than the replace deadline means the group ran
# on a reduced quorum past the budget the operator set.  Same env knob
# the controller uses, so doctor and healer agree.
def _replace_deadline_us() -> float:
    return knob_float("MRT_PLACE_REPLACE_DEADLINE_S") * 1e6


# SANITIZE record code → violation kind (sanitize.py writes them).
_SANITIZE_KINDS = {v: k for k, v in flightrec.SANITIZE_KIND_CODES.items()}

# TAIL record code → queue-wait name (tail.py writes them).
_TAIL_WAITS = {v: k for k, v in flightrec.TAIL_WAIT_CODES.items()}


def _covering_window(
    windows: List[Dict[str, Any]], ts: float,
) -> Optional[Dict[str, Any]]:
    """The nemesis fault window active at host-clock ``ts`` — exact
    containment first; else the latest window that STARTED before
    ``ts`` (a wedge is declared stall_ticks scrapes after its cause,
    and may outlive a short window by detection lag)."""
    best = None
    for w in windows:
        t0 = w.get("t_start_us")
        if t0 is None or t0 > ts:
            continue
        t1 = w.get("t_stop_us")
        if t1 is not None and t1 >= ts:
            return w  # contains ts
        if best is None or t0 > best.get("t_start_us", 0):
            best = w
    return best

# OVERLOAD record codes (overload.py writes them).
_OVL_STAGE = flightrec.OVERLOAD_KIND_CODES["stage_p99"]
_OVL_GAUGE = flightrec.OVERLOAD_KIND_CODES["gauge"]
_OVL_CTX = flightrec.OVERLOAD_KIND_CODES["gauge_ctx"]
_OVL_BROWNOUT = flightrec.OVERLOAD_KIND_CODES["brownout"]

# Brownout states (overload.py BrownoutMachine) named for the note.
_BROWNOUT_NAMES = {0: "healthy", 1: "shedding", 2: "brownout"}

# CPU-saturation evidence bound: a PROF breadcrumb (profile.py, ~1/s)
# carries process CPU busy per-mille of wall in its code field; a
# collapse window whose peak busy reaches this is reclassified from
# "queueing collapse" to "cpu saturation" — the queues diverged because
# the CPU could not keep up, and the breadcrumb's tag names the hot
# function.  ~850‰ rather than 1000‰: the sampler's 1 s windows
# straddle the onset, diluting the pegged fraction.
def _cpusat_permille() -> int:
    return knob_int("MRT_CPUSAT_PERMILLE")


# -- loading ---------------------------------------------------------------


def load_bundle(path: str) -> Dict[str, Any]:
    """Load a bundle dir, a directory of rings, or one ``.ring`` file
    into ``{"dir", "manifest", "snapshots", "windows", "tails",
    "rings"}``.  Unreadable rings are skipped with a note in
    ``"skipped"`` — one corrupt file must not block the rest of the
    postmortem."""
    out: Dict[str, Any] = {
        "dir": path, "manifest": {}, "snapshots": {}, "windows": [],
        "tails": {}, "rings": [], "skipped": [],
    }
    if os.path.isfile(path):
        ring_paths = [path]
        out["dir"] = os.path.dirname(path) or "."
    else:
        for name in ("manifest.json", "snapshots.json", "windows.json",
                     "tails.json"):
            p = os.path.join(path, name)
            if os.path.exists(p):
                try:
                    with open(p) as f:
                        out[name.split(".", 1)[0]] = json.load(f)
                except (OSError, ValueError) as exc:
                    out["skipped"].append(f"{name}: {exc}")
        ring_paths = sorted(
            glob.glob(os.path.join(path, "rings", "*.ring"))
            or glob.glob(os.path.join(path, "*.ring"))
        )
    for rp in ring_paths:
        try:
            ring = flightrec.read_ring(rp)
        except (OSError, ValueError) as exc:
            out["skipped"].append(f"{os.path.basename(rp)}: {exc}")
            continue
        ring["path"] = rp
        out["rings"].append(ring)
    return out


def _pid_offsets(manifest: Dict[str, Any]) -> Dict[int, float]:
    """pid → clock offset (remote − host, µs) via the manifest's
    addr→offset and addr→ident tables; the collecting host is 0."""
    offs: Dict[int, float] = {}
    idents = manifest.get("idents") or {}
    offsets = manifest.get("offsets_us") or {}
    for addr, ident in idents.items():
        off = offsets.get(addr)
        pid = int(ident.get("pid", -1))
        if off is not None and pid > 0:
            offs[pid] = float(off)
    host_pid = manifest.get("host_pid")
    if host_pid:
        offs[int(host_pid)] = 0.0
    return offs


def _pid_addr(manifest: Dict[str, Any], pid: int) -> Optional[str]:
    for addr, ident in (manifest.get("idents") or {}).items():
        if int(ident.get("pid", -1)) == pid:
            return addr
    return None


# -- per-ring + fleet analysis ---------------------------------------------


def _last(records: List[Record], etype: int) -> Optional[Record]:
    for r in reversed(records):
        if r["type"] == etype:
            return r
    return None


def _max_burst(
    ts_list: List[float], window_us: float = BURST_WINDOW_US,
) -> Tuple[int, float]:
    """Densest ``window_us`` window over sorted timestamps:
    ``(count, window_start_ts)``."""
    best, best_ts = 0, 0.0
    lo = 0
    for hi, t in enumerate(ts_list):
        while t - ts_list[lo] > window_us:
            lo += 1
        if hi - lo + 1 > best:
            best, best_ts = hi - lo + 1, ts_list[lo]
    return best, best_ts


def analyze(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """Run every per-ring analysis plus the fleet-wide anomaly merge.

    Returns ``{"procs": [per-ring dict...], "anomalies": [...],
    "first_anomaly": ... | None, "lag": {addr: ...}}``.  Anomaly
    timestamps are host-clock µs when the manifest provides offsets,
    else the ring's own clock (flagged ``aligned: False``)."""
    manifest = bundle.get("manifest") or {}
    offsets = _pid_offsets(manifest)
    procs: List[Dict[str, Any]] = []
    anomalies: List[Dict[str, Any]] = []

    for ring in bundle["rings"]:
        recs: List[Record] = ring["records"]
        pid = ring["pid"]
        off = offsets.get(pid)
        addr = _pid_addr(manifest, pid)
        label = f"{ring['name'] or 'pid' + str(pid)}" + (
            f" @ {addr}" if addr else ""
        )

        def aligned(ts: float, _off: Optional[float] = off) -> float:
            return ts - _off if _off is not None else ts

        info: Dict[str, Any] = {
            "pid": pid, "name": ring["name"], "addr": addr,
            "label": label, "path": ring["path"],
            "records": len(recs), "torn": ring["torn"],
            "slots": ring["slots"], "clean_close": ring["clean_close"],
            "aligned": off is not None,
        }
        if not recs:
            procs.append(info)
            continue
        info["first_seq"] = recs[0]["seq"]
        info["last_seq"] = recs[-1]["seq"]
        info["last_event"] = recs[-1]

        last_commit = _last(recs, flightrec.COMMIT)
        if last_commit is not None:
            info["last_commit"] = last_commit
        last_append = _last(recs, flightrec.WAL_APPEND)
        last_fsync = _last(recs, flightrec.WAL_FSYNC)
        if last_append is not None:
            appended = last_append["a"]
            synced = last_fsync["a"] if last_fsync is not None else 0
            info["wal"] = {"appended": appended, "synced": synced,
                           "gap": appended - synced}
        roles: Dict[int, Record] = {}
        for r in recs:
            if r["type"] == flightrec.ROLE:
                roles[r["code"]] = r
        if roles:
            info["roles"] = {
                peer: {"role": r["a"], "term": r["b"], "commit": r["c"]}
                for peer, r in sorted(roles.items())
            }
        chaos_ts: Dict[str, List[float]] = {}
        for r in recs:
            if r["type"] == flightrec.CHAOS:
                chaos_ts.setdefault(r["tag"], []).append(r["ts"])
        bursts = {}
        for path_tag, ts_list in chaos_ts.items():
            n, t0 = _max_burst(ts_list)
            bursts[path_tag] = {
                "total": len(ts_list), "max_burst": n,
                "burst_at": aligned(t0),
            }
        if bursts:
            info["chaos"] = bursts

        # -- anomaly extraction (all timestamps aligned when possible)
        if not ring["clean_close"]:
            last = recs[-1]
            what = f"last event {last['type_name']} seq {last['seq']}"
            if last_commit is not None:
                what += f"; last commit {_fmt_commit(last_commit)}"
            anomalies.append({
                "ts": aligned(last["ts"]), "proc": label,
                "kind": "unclean_death", "detail": what,
                "aligned": off is not None,
            })
        if info.get("wal", {}).get("gap", 0) > 0:
            gap = info["wal"]["gap"]
            anomalies.append({
                "ts": aligned(last_append["ts"]), "proc": label,
                "kind": "fsync_gap",
                "detail": (
                    f"{gap} WAL append(s) past last fsync "
                    f"(appended seq {info['wal']['appended']}, "
                    f"synced {info['wal']['synced']}) — unacked writes "
                    f"staged at death"
                ),
                "aligned": off is not None,
            })
        for path_tag, bst in bursts.items():
            if bst["max_burst"] >= BURST_MIN:
                anomalies.append({
                    "ts": bst["burst_at"], "proc": label,
                    "kind": "chaos_burst",
                    "detail": (
                        f"{bst['max_burst']} faults on '{path_tag}' "
                        f"within {BURST_WINDOW_US / 1e6:.0f}s "
                        f"({bst['total']} total)"
                    ),
                    "aligned": off is not None,
                })
        for r in recs:
            if r["type"] != flightrec.SANITIZE:
                continue
            kind = _SANITIZE_KINDS.get(r["code"], f"kind{r['code']}")
            detail = f"runtime sanitizer: {kind} on '{r['tag']}'"
            if r["a"] or r["b"]:
                detail += f" (value {r['a']}, limit {r['b']})"
            anomalies.append({
                "ts": aligned(r["ts"]), "proc": label,
                "kind": "sanitizer_violation",
                "detail": detail,
                "aligned": off is not None,
            })
        # Profiler breadcrumbs (PROF, ~1/s): cumulative samples,
        # distinct stacks, process CPU busy per-mille per window
        # (code), hottest leaf function (tag) — the sampler's black
        # box.  Summarized here; consumed below to discriminate the
        # overload diagnosis.
        profs = [r for r in recs if r["type"] == flightrec.PROF]
        if profs:
            info["profile"] = {
                "records": len(profs),
                "samples": profs[-1]["a"],
                "peak_busy_permille": max(r["code"] for r in profs),
                "hottest": next(
                    (r["tag"] for r in reversed(profs) if r["tag"]), ""
                ),
            }
        # Tail-microscope breadcrumbs (TAIL, tail.py): over-SLO and
        # new-slowest completions — code=dominant-wait, a=total_us,
        # b=wait_us, c=carrying engine tick, tag=rid.  The ring's
        # slowest request survives SIGKILL; summarized per ring, and
        # escalated to a tail_outlier anomaly when it breached the SLO
        # — anchored on the request, naming the dominating wait and
        # (when the ledger covers it) the nemesis window it rode out.
        tails = [r for r in recs if r["type"] == flightrec.TAIL]
        if tails:
            slow = max(tails, key=lambda r: r["a"])
            wait = _TAIL_WAITS.get(slow["code"], f"code{slow['code']}")
            info["tail"] = {
                "records": len(tails),
                "slowest_ms": round(slow["a"] / 1e3, 3),
                "dominant_wait": wait,
                "rid": slow["tag"],
                "tick": slow["c"],
            }
            if slow["a"] / 1e3 > knob_float("MRT_TAIL_SLO_MS"):
                detail = (
                    f"slowest request {slow['tag'] or '<untagged>'}: "
                    f"{slow['a'] / 1e3:.1f} ms total, "
                    f"{slow['b'] / 1e3:.1f} ms in the '{wait}' wait"
                    + (f", engine tick {slow['c']}" if slow["c"] else "")
                )
                win = _covering_window(
                    bundle.get("windows") or [], aligned(slow["ts"])
                )
                if win is not None:
                    detail += (
                        f"; during fault window '{win['kind']}' on "
                        f"proc(s) {win.get('procs')}"
                    )
                anomalies.append({
                    "ts": aligned(slow["ts"]), "proc": label,
                    "kind": "tail_outlier", "detail": detail,
                    "aligned": off is not None,
                })
        # Overload-watch trips → ONE collapse anomaly per ring,
        # anchored on the FIRST saturated stage (a collapse can leave
        # hundreds of trip records; the first one names where the
        # queueing started).  The paired gauge_ctx record supplies the
        # queue the collapse backed up into.  The PROF breadcrumbs
        # then pick the diagnosis: pegged CPU during the collapse
        # window → "cpu_saturation" (the stage's CPU-seconds fill the
        # wall window; fix the hot function); CPU idle → the classic
        # "queueing_collapse" (something downstream stalled).
        over = [r for r in recs if r["type"] == flightrec.OVERLOAD]
        # Brownout transitions are control decisions, not bound trips —
        # excluded from the collapse evidence so the two notes stay
        # distinct: "queueing collapse" = queues diverged; "shedding
        # engaged" = the admission plane acted on it.
        trips = [r for r in over
                 if r["code"] not in (_OVL_CTX, _OVL_BROWNOUT)]
        browns = [r for r in over if r["code"] == _OVL_BROWNOUT]
        escalations = [r for r in browns if r["a"] > r["b"]]
        if escalations:
            first_up = escalations[0]
            peak = max(r["a"] for r in browns)
            detail = (
                f"shedding engaged: brownout machine "
                f"{_BROWNOUT_NAMES.get(first_up['b'], first_up['b'])} → "
                f"{_BROWNOUT_NAMES.get(first_up['a'], first_up['a'])} "
                f"({first_up['c']} trip(s) that tick); peak state "
                f"{_BROWNOUT_NAMES.get(peak, peak)}, "
                f"{len(browns)} transition(s) total — admission "
                f"tightened; user-lane requests were shed with "
                f"retry_after hints (this is the overload plane "
                f"WORKING, distinct from an uncontrolled collapse)"
            )
            anomalies.append({
                "ts": aligned(first_up["ts"]), "proc": label,
                "kind": "shedding_engaged", "detail": detail,
                "aligned": off is not None,
            })
            info["brownout"] = {
                "transitions": len(browns),
                "peak": _BROWNOUT_NAMES.get(peak, str(peak)),
            }
        if trips:
            first = trips[0]
            gauge = next(
                (r for r in over
                 if r["code"] == _OVL_CTX and r["seq"] >= first["seq"]),
                None,
            ) or next(
                (r for r in over
                 if r["code"] == _OVL_GAUGE and r is not first),
                None,
            )
            if first["code"] == _OVL_STAGE:
                what = (
                    f"first saturated stage "
                    f"'{first['tag']}' windowed p99 "
                    f"{first['a'] / 1e3:.1f}ms > bound "
                    f"{first['b'] / 1e3:.1f}ms "
                    f"({first['c']} sample(s) in window)"
                )
            else:
                what = (
                    f"queue gauge '{first['tag']}' "
                    f"depth {first['a']} > bound {first['b']}"
                )
            if gauge is not None:
                what += (
                    f"; queue gauge {gauge['tag']}={gauge['a']}"
                    + (f" (bound {gauge['b']})" if gauge["b"] else "")
                )
            what += f"; {len(trips)} overload trip(s) total"
            # Discrimination: PROF breadcrumbs from the first trip to
            # the ring's end (fall back to the whole ring if the
            # sampler died before the trip landed).
            wprofs = [r for r in profs if r["ts"] >= first["ts"]] or profs
            busy = max((r["code"] for r in wprofs), default=0)
            hot = next(
                (r["tag"] for r in reversed(wprofs) if r["tag"]), ""
            )
            if busy >= _cpusat_permille():
                kind = "cpu_saturation"
                detail = (
                    f"CPU saturation: {what}; process CPU busy "
                    f"{busy}‰ of wall at peak during the collapse"
                    + (f"; profiler hottest function '{hot}'"
                       if hot else "")
                    + " — the stage's CPU-seconds fill the wall window "
                      "(host-bound): the queue bound is the symptom, "
                      "the hot function is the fix"
                )
            else:
                kind = "queueing_collapse"
                detail = f"queueing collapse: {what}"
                if profs:
                    detail += (
                        f"; CPU idle while queues diverged (peak busy "
                        f"{busy}‰) — a downstream stall, not a CPU "
                        f"shortage"
                    )
            anomalies.append({
                "ts": aligned(first["ts"]), "proc": label,
                "kind": kind, "detail": detail,
                "aligned": off is not None,
            })
            info["overload"] = {
                "trips": len(trips),
                "first": first["tag"],
                "gauge": gauge["tag"] if gauge is not None else None,
                "diagnosis": kind,
                "peak_busy_permille": busy,
            }
        # Placement thrash: PLACE records (the controller's decision
        # log) grouped by gid; the densest window per gid against the
        # thrash bound.  The controller's own ring is usually the only
        # one carrying these.
        place_ts: Dict[int, List[float]] = {}
        for r in recs:
            if r["type"] == flightrec.PLACE:
                place_ts.setdefault(r["code"], []).append(r["ts"])
        if place_ts:
            info["placements"] = {
                gid: len(ts) for gid, ts in sorted(place_ts.items())
            }
        for gid, ts_list in sorted(place_ts.items()):
            n, t0 = _max_burst(ts_list, THRASH_WINDOW_US)
            if n >= THRASH_MIN:
                anomalies.append({
                    "ts": aligned(t0), "proc": label,
                    "kind": "placement_thrash",
                    "detail": (
                        f"group {gid} moved {n} times within "
                        f"{THRASH_WINDOW_US / 1e6:.0f}s "
                        f"({len(ts_list)} move(s) total) — the planner "
                        f"is oscillating; raise MRT_PLACE_COOLDOWN_S / "
                        f"MRT_PLACE_MIN_GAIN"
                    ),
                    "aligned": off is not None,
                })
        # Shipped-state loss window: only rings that actually shipped
        # (SHIP records present) are judged — a fleet without the state
        # plane must not produce false positives.  For each shipped
        # group, the gap between its last acked shipment and the ring's
        # end is the data the standbys never saw; on an unclean death a
        # gap past the shipping window is exactly "data loss window
        # exceeded".
        ship_last: Dict[int, Record] = {}
        n_ship = 0
        for r in recs:
            if r["type"] == flightrec.SHIP:
                ship_last[r["code"]] = r
                n_ship += 1
        if ship_last:
            info["shipments"] = {
                gid: {"last_frontier": r["c"], "last_kind": r["tag"]}
                for gid, r in sorted(ship_last.items())
            }
            info["ship_records"] = n_ship
        if ship_last and not ring["clean_close"]:
            end_ts = recs[-1]["ts"]
            window = _ship_window_us()
            for gid, r in sorted(ship_last.items()):
                gap = end_ts - r["ts"]
                if gap > window:
                    anomalies.append({
                        "ts": aligned(r["ts"]), "proc": label,
                        "kind": "ship_window_exceeded",
                        "detail": (
                            f"data loss window exceeded: group {gid}'s "
                            f"last shipment ({r['tag']}, frontier "
                            f"{r['c']}) was {gap / 1e6:.1f}s before "
                            f"death > window "
                            f"{window / 1e6:.1f}s — writes in the gap "
                            f"died unshipped"
                        ),
                        "aligned": off is not None,
                    })
        # Wedged leadership: WEDGE records (wedge.py watchdog) grouped
        # by group — ONE anomaly per wedged group, anchored on the
        # wedge ONSET, naming the stalled group, the stuck leader (the
        # record tag carries "p<peer>@t<term>"), and the nemesis fault
        # window that caused it (windows.json, same host clock the
        # anomaly is aligned to).
        wedge_by_g: Dict[int, List[Record]] = {}
        for r in recs:
            if r["type"] == flightrec.WEDGE:
                wedge_by_g.setdefault(r["code"], []).append(r)
        if wedge_by_g:
            info["wedges"] = {
                g: {
                    "records": len(rs),
                    "peak_stall": max(r["a"] for r in rs),
                    "leader": rs[0]["tag"],
                }
                for g, rs in sorted(wedge_by_g.items())
            }
        for g, rs in sorted(wedge_by_g.items()):
            first, last = rs[0], rs[-1]
            onset = aligned(first["ts"])
            span_s = (last["ts"] - first["ts"]) / 1e6
            detail = (
                f"wedged leadership: group {g} commit frontier stalled "
                f"at {first['b']} with {first['c']} proposal(s) "
                f"pending; stuck leader {first['tag']}; "
                f"{len(rs)} wedge record(s) over {span_s:.1f}s, peak "
                f"stall {max(r['a'] for r in rs)} scrape(s)"
            )
            win = (
                _covering_window(bundle.get("windows") or [], onset)
                if off is not None else None
            )
            if win is not None:
                t1 = win.get("t_stop_us")
                detail += (
                    f"; during fault window '{win['kind']}' on "
                    f"proc(s) {win.get('procs')} "
                    f"(t={win.get('t_start_us', 0):.0f}–"
                    + (f"{t1:.0f}us" if t1 is not None else "open")
                    + ")"
                )
            anomalies.append({
                "ts": onset, "proc": label,
                "kind": "wedged_leadership", "detail": detail,
                "aligned": off is not None,
            })
        # Degraded quorum: CONFIG records (placement.py healer) grouped
        # by group.  A replace-replica reconfig runs the group on a
        # reduced quorum from the voter's death until "done"; flag any
        # reconfig still OPEN at the ring's end (begun, never done or
        # aborted — on an unclean controller death that's a heal the
        # successor must resume) and any that ran past the replace
        # deadline even when it eventually finished.
        cfg_by_g: Dict[int, List[Record]] = {}
        for r in recs:
            if r["type"] == flightrec.CONFIG:
                cfg_by_g.setdefault(r["code"], []).append(r)
        if cfg_by_g:
            info["reconfigs"] = {
                g: {
                    "records": len(rs),
                    "last_phase": rs[-1]["tag"],
                    "dead_peer": rs[0]["a"],
                    "new_peer": rs[0]["b"],
                }
                for g, rs in sorted(cfg_by_g.items())
            }
        deadline_us = _replace_deadline_us()
        for g, rs in sorted(cfg_by_g.items()):
            first, last = rs[0], rs[-1]
            onset = aligned(first["ts"])
            span_us = last["ts"] - first["ts"]
            open_end = last["tag"] not in ("done", "abort")
            overran = span_us > deadline_us
            if not open_end and not overran:
                continue
            if open_end:
                what = (
                    f"reconfig still open at ring end (last phase "
                    f"'{last['tag']}' after {span_us / 1e6:.1f}s"
                    + ("" if ring["clean_close"]
                       else "; controller died mid-reconfig — successor "
                            "must resume the replicated intent")
                    + ")"
                )
            else:
                what = (
                    f"reconfig took {span_us / 1e6:.1f}s > deadline "
                    f"{deadline_us / 1e6:.0f}s before '{last['tag']}'"
                )
            detail = (
                f"degraded quorum: group {g} lost voter "
                f"{first['a']} (replacement peer {first['b']}, epoch "
                f"{first['c']}); {what}; {len(rs)} config record(s)"
            )
            win = (
                _covering_window(bundle.get("windows") or [], onset)
                if off is not None else None
            )
            if win is not None:
                detail += (
                    f"; during fault window '{win['kind']}' on "
                    f"proc(s) {win.get('procs')}"
                )
            anomalies.append({
                "ts": onset, "proc": label,
                "kind": "degraded_quorum", "detail": detail,
                "aligned": off is not None,
            })
        torn = ring["torn"]
        if torn > 1:
            # One torn slot is the expected SIGKILL signature; more
            # means the file itself took damage — say so.
            anomalies.append({
                "ts": aligned(recs[-1]["ts"]), "proc": label,
                "kind": "torn_slots",
                "detail": f"{torn} slots failed checksum",
                "aligned": off is not None,
            })
        procs.append(info)

    # Missing processes per the final scrape (dead at collection).
    lag: Dict[str, Any] = {}
    for addr, snap in (bundle.get("snapshots") or {}).items():
        if snap.get("missing"):
            lag[addr] = {"missing": True, "pid": snap.get("pid")}
            continue
        groups = snap.get("groups")
        if not groups:
            continue
        commit = groups.get("commit") or []
        applied = groups.get("applied") or []
        pairs = list(zip(commit, applied))
        if not pairs:
            continue
        worst = max(range(len(pairs)), key=lambda i: pairs[i][0] - pairs[i][1])
        lag[addr] = {
            "max_lag": pairs[worst][0] - pairs[worst][1],
            "group": worst,
            "commit": pairs[worst][0],
            "applied": pairs[worst][1],
        }

    anomalies.sort(key=lambda a: a["ts"])
    return {
        "procs": procs,
        "anomalies": anomalies,
        "first_anomaly": anomalies[0] if anomalies else None,
        "lag": lag,
    }


# -- Perfetto export -------------------------------------------------------


def rings_to_trace(bundle: Dict[str, Any]) -> Tracer:
    """One clock-aligned Chrome trace from every ring in the bundle."""
    manifest = bundle.get("manifest") or {}
    offsets = _pid_offsets(manifest)
    total = sum(len(r["records"]) for r in bundle["rings"])
    # ×2: a PROF record can emit a counter AND a hottest-function
    # instant; every other type emits at most one event.
    out = Tracer(max_events=2 * total + 16 * max(1, len(bundle["rings"])))
    for ring in bundle["rings"]:
        pid = ring["pid"]
        off = offsets.get(pid, 0.0)
        last_hot = ""
        addr = _pid_addr(manifest, pid)
        tagbits = "" if pid in offsets else " (unaligned clock)"
        out.process_name(
            pid, f"{ring['name'] or 'pid' + str(pid)}"
                 + (f" @ {addr}" if addr else "") + tagbits,
        )
        for r in ring["records"]:
            ts = r["ts"] - off
            t = r["type"]
            if t in (flightrec.RPC_HANDLE, flightrec.RPC_CLIENT):
                track = "rpc" if t == flightrec.RPC_HANDLE else "rpc_client"
                out.span(r["tag"] or r["type_name"], ts - r["a"], r["a"],
                         track=track, pid=pid, ok=r["b"], seq=r["seq"])
            elif t == flightrec.RPC_OUT:
                out.instant(r["tag"] or "rpc_out", ts, track="rpc_out",
                            pid=pid, req_id=r["a"], bytes=r["b"])
            elif t == flightrec.WAL_APPEND:
                out.counter("wal_appended", ts, {"seq": r["a"]}, pid=pid,
                            track="wal")
            elif t == flightrec.WAL_FSYNC:
                out.counter("wal_synced", ts, {"seq": r["a"]}, pid=pid,
                            track="wal")
            elif t in (flightrec.STATE, flightrec.TICK):
                out.counter("commits_total", ts,
                            {"commits": r["a"] if t == flightrec.STATE
                             else r["c"]}, pid=pid, track="engine")
            elif t == flightrec.COMMIT:
                out.instant("commit", ts, track="commit", pid=pid,
                            group=r["code"], client=r["a"], cmd=r["b"],
                            rid=r["tag"])
            elif t == flightrec.CHAOS:
                out.instant(f"chaos:{r['tag']}", ts, track="chaos",
                            pid=pid, kind=r["code"])
            elif t == flightrec.ROLE:
                out.instant(f"role:peer{r['code']}", ts, track="raft",
                            pid=pid, role=r["a"], term=r["b"],
                            commit=r["c"])
            elif t == flightrec.OVERLOAD:
                out.instant(f"overload:{r['tag']}", ts, track="overload",
                            pid=pid, kind=r["code"], value=r["a"],
                            bound=r["b"])
            elif t == flightrec.PLACE:
                out.instant(
                    f"place:g{r['code']}", ts, track="placement",
                    pid=pid, group=r["code"], src=r["a"], dst=r["b"],
                    version=r["c"], reason=r["tag"],
                )
            elif t == flightrec.SHIP:
                out.instant(
                    f"ship:g{r['code']}", ts, track="ship",
                    pid=pid, group=r["code"], records=r["a"],
                    bytes=r["b"], frontier=r["c"], kind=r["tag"],
                )
            elif t == flightrec.WEDGE:
                out.instant(
                    f"wedge:g{r['code']}", ts, track="wedge",
                    pid=pid, group=r["code"], stall=r["a"],
                    commit=r["b"], backlog=r["c"], leader=r["tag"],
                )
            elif t == flightrec.CONFIG:
                out.instant(
                    f"config:g{r['code']}", ts, track="config",
                    pid=pid, group=r["code"], dead_peer=r["a"],
                    new_peer=r["b"], epoch=r["c"], phase=r["tag"],
                )
            elif t == flightrec.PROF:
                out.counter(
                    "profiler", ts,
                    {"busy_permille": r["code"], "samples": r["a"],
                     "stacks": r["b"], "overflow": r["c"]},
                    pid=pid, track="profile",
                )
                if r["tag"] and r["tag"] != last_hot:
                    last_hot = r["tag"]
                    out.instant(f"hot:{r['tag']}", ts, track="profile",
                                pid=pid, busy_permille=r["code"])
            elif t == flightrec.NODE_CLOSE:
                out.instant(f"close:{r['tag']}", ts, track="marks",
                            pid=pid, node=r["tag"], clean=True)
            elif t == flightrec.TAIL:
                # Slow-request breadcrumb: span back over the request's
                # whole lifetime so the outlier overlaps the pump ticks
                # and RPC spans that produced it.
                out.span(
                    f"tail:{r['tag'] or 'request'}", ts - r["a"], r["a"],
                    track="tail", pid=pid,
                    wait=_TAIL_WAITS.get(r["code"], r["code"]),
                    wait_us=r["b"], tick=r["c"], seq=r["seq"],
                )
            elif t == flightrec.MARK:
                out.instant(f"mark:{r['tag']}", ts, track="marks",
                            pid=pid, tag=r["tag"])
            else:  # future types: show, don't drop
                out.instant(r["type_name"], ts, track="marks", pid=pid,
                            tag=r["tag"])
    return out


def rid_events(
    bundle: Dict[str, Any], rid: str,
) -> List[Tuple[str, Record]]:
    """Every ring record tagged with ``rid`` (the request's commit
    trail across processes), as ``(ring label, record)`` in seq order
    per ring."""
    hits: List[Tuple[str, Record]] = []
    for ring in bundle["rings"]:
        label = ring["name"] or f"pid{ring['pid']}"
        for r in ring["records"]:
            if r["tag"] == rid:
                hits.append((label, r))
    return hits


# -- report ----------------------------------------------------------------

_ROLE_NAMES = {0: "follower", 1: "candidate", 2: "leader"}


def _fmt_commit(r: Record) -> str:
    # Client ids are unsigned 64-bit on the wire; the ring stores the
    # low 64 bits two's-complement (flightrec._i64) — undo that here.
    client = r["a"] & 0xFFFFFFFFFFFFFFFF
    return (
        f"group {r['code']} client {client:#x} cmd {r['b']}"
        + (f" rid {r['tag']}" if r["tag"] else "")
    )


def build_report(bundle: Dict[str, Any], analysis: Dict[str, Any]) -> str:
    manifest = bundle.get("manifest") or {}
    lines: List[str] = []
    add = lines.append
    add("=" * 72)
    add(f"POSTMORTEM  {bundle['dir']}")
    if manifest.get("reason"):
        add(f"reason: {manifest['reason']}")
    if manifest.get("addrs"):
        add(
            f"fleet: {len(manifest['addrs'])} process(es), "
            f"{len(bundle['rings'])} ring(s), "
            f"{len(manifest.get('unreachable') or [])} unreachable at "
            f"collection"
        )
    add("=" * 72)

    fa = analysis["first_anomaly"]
    if fa is not None:
        add("")
        add("FIRST ANOMALY")
        mark = "" if fa["aligned"] else " (unaligned clock)"
        add(f"  t={fa['ts']:.0f}us{mark}  [{fa['proc']}]  {fa['kind']}")
        add(f"  {fa['detail']}")
    else:
        add("")
        add("no anomalies detected (all rings closed cleanly, no fsync "
            "gaps, no chaos bursts)")

    add("")
    add("PROCESSES")
    for p in analysis["procs"]:
        death = "clean close" if p["clean_close"] else "UNCLEAN DEATH"
        add(f"  {p['label']}  (pid {p['pid']})  — {death}")
        add(
            f"    ring: {p['records']} intact record(s)"
            f" / {p['slots']} slots, {p['torn']} torn"
            + ("" if p["aligned"] else ", clock unaligned")
        )
        if "last_event" in p:
            le = p["last_event"]
            add(f"    last event: {le['type_name']} seq {le['seq']}")
        if "last_commit" in p:
            add(f"    last commit: {_fmt_commit(p['last_commit'])}")
        if "wal" in p:
            w = p["wal"]
            gap = (
                f"  ** {w['gap']} append(s) NOT fsync'd **"
                if w["gap"] > 0 else ""
            )
            add(f"    wal: appended seq {w['appended']}, "
                f"synced {w['synced']}{gap}")
        for peer, r in (p.get("roles") or {}).items():
            add(
                f"    raft peer {peer}: "
                f"{_ROLE_NAMES.get(r['role'], r['role'])} "
                f"term {r['term']} commit {r['commit']}"
            )
        for path_tag, b in (p.get("chaos") or {}).items():
            add(
                f"    chaos '{path_tag}': {b['total']} fault(s), "
                f"max burst {b['max_burst']}/"
                f"{BURST_WINDOW_US / 1e6:.0f}s"
            )
        if "overload" in p:
            o = p["overload"]
            add(
                f"    overload: {o['trips']} trip(s), first saturated: "
                f"{o['first']}"
                + (f", queue gauge {o['gauge']}" if o["gauge"] else "")
                + (f" — diagnosed {o['diagnosis']} "
                   f"(peak busy {o['peak_busy_permille']}‰)"
                   if "diagnosis" in o else "")
            )
        if "profile" in p:
            pr = p["profile"]
            add(
                f"    profiler: {pr['records']} breadcrumb(s), "
                f"{pr['samples']} sample(s), peak busy "
                f"{pr['peak_busy_permille']}‰"
                + (f", hottest {pr['hottest']}" if pr["hottest"] else "")
            )
        if "tail" in p:
            tl = p["tail"]
            add(
                f"    tail: {tl['records']} breadcrumb(s), slowest "
                f"{tl['slowest_ms']:.1f} ms"
                + (f" (rid {tl['rid']})" if tl["rid"] else "")
                + f", dominant wait {tl['dominant_wait']}"
                + (f", tick {tl['tick']}" if tl["tick"] else "")
            )
        if "shipments" in p:
            gids = ", ".join(
                f"g{gid}@{d['last_frontier']}"
                for gid, d in p["shipments"].items()
            )
            add(
                f"    shipped state: {p['ship_records']} shipment(s), "
                f"last frontiers {gids}"
            )
        for g, w in (p.get("wedges") or {}).items():
            add(
                f"    wedged: group {g} leader {w['leader']}, "
                f"{w['records']} record(s), peak stall "
                f"{w['peak_stall']} scrape(s)"
            )
        for g, c in (p.get("reconfigs") or {}).items():
            add(
                f"    reconfig: group {g} voter {c['dead_peer']} → "
                f"peer {c['new_peer']}, last phase '{c['last_phase']}' "
                f"({c['records']} record(s))"
            )

    if analysis["lag"]:
        add("")
        add("COMMIT/APPLY AT FINAL SCRAPE")
        for addr, d in sorted(analysis["lag"].items()):
            if d.get("missing"):
                add(f"  {addr}: MISSING (dead at collection, "
                    f"pid {d.get('pid')})")
            else:
                add(
                    f"  {addr}: max lag {d['max_lag']} "
                    f"(group {d['group']}: commit {d['commit']}, "
                    f"applied {d['applied']})"
                )

    if analysis["anomalies"]:
        add("")
        add("ANOMALY TIMELINE (host-clock us)")
        for a in analysis["anomalies"]:
            mark = "" if a["aligned"] else " ~"
            add(f"  t={a['ts']:>16.0f}{mark}  [{a['proc']}] "
                f"{a['kind']}: {a['detail']}")

    if bundle["skipped"]:
        add("")
        add("SKIPPED INPUTS")
        for s in bundle["skipped"]:
            add(f"  {s}")
    add("")
    return "\n".join(lines)


# -- CLI -------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m multiraft_tpu.analysis.postmortem",
        description="Flight-recorder postmortem doctor",
    )
    ap.add_argument("bundle", help="bundle dir, rings dir, or .ring file")
    ap.add_argument(
        "--trace-out", default=None,
        help="Perfetto trace path (default <bundle>/flight_trace.json.gz;"
             " 'none' to skip)",
    )
    ap.add_argument(
        "--rid", default=None,
        help="also print every ring record tagged with this request id",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the analysis as JSON instead of the text report",
    )
    ns = ap.parse_args(argv)

    if not os.path.exists(ns.bundle):
        print(f"postmortem: no such bundle: {ns.bundle}", file=sys.stderr)
        return 2
    bundle = load_bundle(ns.bundle)
    if not bundle["rings"] and not bundle["snapshots"]:
        print(
            f"postmortem: {ns.bundle}: no readable rings or snapshots"
            + (f" ({'; '.join(bundle['skipped'])})"
               if bundle["skipped"] else ""),
            file=sys.stderr,
        )
        return 2
    analysis = analyze(bundle)

    if ns.json:
        print(json.dumps(analysis, indent=2, sort_keys=True, default=str))
    else:
        report = build_report(bundle, analysis)
        print(report)
        if os.path.isdir(bundle["dir"]):
            try:
                with open(os.path.join(bundle["dir"], "report.txt"),
                          "w") as f:
                    f.write(report)
            except OSError:
                pass

    if ns.rid:
        hits = rid_events(bundle, ns.rid)
        print(f"rid {ns.rid}: {len(hits)} record(s)")
        for label, r in hits:
            print(
                f"  [{label}] seq {r['seq']} {r['type_name']} "
                f"code={r['code']} a={r['a']} b={r['b']} ts={r['ts']:.0f}"
            )

    if ns.trace_out != "none" and bundle["rings"]:
        trace_path = ns.trace_out or os.path.join(
            bundle["dir"], "flight_trace.json.gz"
        )
        try:
            rings_to_trace(bundle).save(trace_path)
            print(f"perfetto trace: {trace_path}", file=sys.stderr)
        except OSError as exc:  # pragma: no cover - fs full etc.
            print(f"postmortem: trace write failed: {exc}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
