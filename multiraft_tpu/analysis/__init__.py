"""graftlint: project-native static analysis for the multiraft-tpu
codebase.

``python -m multiraft_tpu.analysis multiraft_tpu/`` lints the package
with every registered rule; ``scripts/check.py`` wraps it together
with ruff/mypy into the one-shot gate, and ``tests/test_analysis.py``
enforces zero unsuppressed findings in tier-1.

See :mod:`.core` for the framework, :mod:`.rules` for the per-bug-class
rules, :mod:`.lockgraph` for the static lock audit, :mod:`.planes` and
:mod:`.registry` for the contract-drift rules (state-plane lifecycle,
record/chaos/capability/knob registries) and :mod:`.lockorder` for the
dynamic recorder used by the chaos tests.
"""

from .core import ALL_RULES, Finding, ModuleInfo, Project, Rule, run
from . import rules as _rules  # noqa: F401  (registration side effect)
from . import lockgraph as _lockgraph  # noqa: F401
from . import dataflow as _dataflow  # noqa: F401
from . import planes as _planes  # noqa: F401
from . import registry as _registry  # noqa: F401
from .dataflow import Dataflow, get_dataflow
from .lockgraph import LockGraph
from .lockorder import LockOrderRecorder, RecordingLock

__all__ = [
    "ALL_RULES",
    "Dataflow",
    "Finding",
    "ModuleInfo",
    "Project",
    "Rule",
    "get_dataflow",
    "run",
    "LockGraph",
    "LockOrderRecorder",
    "RecordingLock",
]
