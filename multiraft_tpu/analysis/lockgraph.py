"""Static lock-graph audit: acquisition-order cycles + unlocked writes.

The threaded transport stack (tcp.py's loop thread + caller threads,
chaos.py's RNG lock, realtime.py's scheduler condition, nemesis.py's
clerk history lock) is exactly the code Go's race detector would watch
in the reference stack.  This module extracts an approximation of the
runtime lock graph from the AST:

* **lock identities** are ``(ClassName, attr)`` for ``self.X =
  threading.Lock()/RLock()/Condition()`` attributes and
  ``(module, name)`` for module-level locks.  This collapses all
  instances of a class onto one node — conservative for cycle
  detection across classes (the interesting case), at the cost of
  false positives for self-edges on per-instance locks, which are
  reported distinctly ("self-cycle") and only when a ``with`` on the
  lock appears lexically inside another ``with`` on the same lock.
* **edges** H → L mean "L acquired while H held": directly nested
  ``with`` blocks, plus calls made under H into methods (same class,
  attribute-typed member objects, module functions) that acquire
  their own locks — followed transitively to depth 4.
* ``lock-order`` findings are cycles in that graph; ``unlocked-write``
  findings are attribute stores outside any lock for attributes that
  are stored under a lock elsewhere in the same class (the classic
  "forgot the lock on one branch" race — chaos.py's block-branch
  counter increment was exactly this).

The static audit is backed by a *dynamic* recorder
(:mod:`.lockorder`) asserted in the chaos tests, so the approximation
has a runtime cross-check.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, Rule, register
from .dataflow import ClassInfo, get_dataflow, is_lock_ctor as _is_lock_ctor

__all__ = [
    "Acquisition",
    "ClassInfo",
    "LockGraph",
    "LockId",
    "get_lock_graph",
]

LockId = Tuple[str, str]  # (scope = class or module stem, attr/name)


@dataclass
class Acquisition:
    lock: LockId
    path: str
    line: int
    method: str


class LockGraph:
    """Per-method acquisitions and the lock-order edge set.

    Class/lock/type collection lives in :mod:`.dataflow` (one shared
    pass per lint run — the serving-path rules read the same tables);
    this class keeps the lock-specific analysis: with-block resolution,
    transitive acquisition closure, and edge construction."""

    def __init__(self, project: Project) -> None:
        self.project = project
        df = get_dataflow(project)
        self.classes: Dict[str, ClassInfo] = df.classes
        self.module_locks: Dict[str, Set[str]] = df.module_locks
        self.module_funcs: Dict[str, Dict[str, ast.FunctionDef]] = (
            df.module_funcs
        )
        # (scope, method) → locks transitively acquired inside
        self._acq_memo: Dict[Tuple[str, str], Set[LockId]] = {}
        # edge → one witness site
        self.edges: Dict[Tuple[LockId, LockId], Acquisition] = {}
        self._build_edges()

    # -- lock resolution ---------------------------------------------------

    def _lock_of_withitem(
        self, ci: Optional[ClassInfo], stem: str, ctx: ast.AST
    ) -> Optional[LockId]:
        if (
            ci is not None
            and isinstance(ctx, ast.Attribute)
            and isinstance(ctx.value, ast.Name)
            and ctx.value.id == "self"
            and ctx.attr in ci.lock_attrs
        ):
            return (ci.name, ctx.attr)
        if isinstance(ctx, ast.Name) and ctx.id in self.module_locks.get(
            stem, ()
        ):
            return (stem, ctx.id)
        return None

    # -- transitive acquisitions per callee --------------------------------

    def _callee_acquires(
        self,
        ci: Optional[ClassInfo],
        stem: str,
        call: ast.Call,
        depth: int,
    ) -> Set[LockId]:
        if depth <= 0:
            return set()
        f = call.func
        # self.meth(...)
        if (
            ci is not None
            and isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and f.attr in ci.methods
        ):
            return self._method_acquires(ci, f.attr, depth)
        # self.attr.meth(...)
        if (
            ci is not None
            and isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Attribute)
            and isinstance(f.value.value, ast.Name)
            and f.value.value.id == "self"
        ):
            target_cls = ci.attr_types.get(f.value.attr)
            tci = self.classes.get(target_cls or "")
            if tci is not None and f.attr in tci.methods:
                return self._method_acquires(tci, f.attr, depth)
        # module_fn(...)
        if isinstance(f, ast.Name) and f.id in self.module_funcs.get(
            stem, ()
        ):
            fn = self.module_funcs[stem][f.id]
            return self._fn_acquires(None, stem, fn, f"{stem}.{f.id}", depth)
        return set()

    def _method_acquires(
        self, ci: ClassInfo, meth: str, depth: int
    ) -> Set[LockId]:
        key = (ci.name, meth)
        if key in self._acq_memo:
            return self._acq_memo[key]
        self._acq_memo[key] = set()  # cycle guard
        acc = self._fn_acquires(
            ci, ci.module, ci.methods[meth], f"{ci.name}.{meth}", depth
        )
        self._acq_memo[key] = acc
        return acc

    def _fn_acquires(
        self,
        ci: Optional[ClassInfo],
        stem: str,
        fn: ast.FunctionDef,
        label: str,
        depth: int,
    ) -> Set[LockId]:
        acc: Set[LockId] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.With):
                for item in n.items:
                    lock = self._lock_of_withitem(
                        ci, stem, item.context_expr
                    )
                    if lock is not None:
                        acc.add(lock)
            elif isinstance(n, ast.Call):
                acc |= self._callee_acquires(ci, stem, n, depth - 1)
        return acc

    # -- edge construction -------------------------------------------------

    def _build_edges(self) -> None:
        for ci in self.classes.values():
            for mname, meth in ci.methods.items():
                self._walk_held(ci, ci.module, meth, mname, [])
        for stem, funcs in self.module_funcs.items():
            mod = next(
                (m for m in self.project.modules if m.name == stem), None
            )
            if mod is None:
                continue
            for fname, fn in funcs.items():
                self._walk_held(None, stem, fn, fname, [])

    def _walk_held(
        self,
        ci: Optional[ClassInfo],
        stem: str,
        node: ast.AST,
        method: str,
        held: List[LockId],
    ) -> None:
        path = ci.path if ci is not None else next(
            (str(m.path) for m in self.project.modules if m.name == stem),
            stem,
        )

        def add_edges(locks: Set[LockId], line: int) -> None:
            for lock in locks:
                for h in held:
                    if h == lock:
                        continue  # re-entry on one lock: self-cycle below
                    key = (h, lock)
                    if key not in self.edges:
                        self.edges[key] = Acquisition(
                            lock=lock, path=path, line=line, method=method
                        )

        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                acquired: List[LockId] = []
                for item in child.items:
                    lock = self._lock_of_withitem(
                        ci, stem, item.context_expr
                    )
                    if lock is not None:
                        add_edges({lock}, child.lineno)
                        acquired.append(lock)
                for sub in child.body:
                    self._walk_held(
                        ci, stem, sub, method, held + acquired
                    )
                continue
            if isinstance(child, ast.Call) and held:
                add_edges(
                    self._callee_acquires(ci, stem, child, 4),
                    child.lineno,
                )
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # nested defs execute later, not under the held locks
                self._walk_held(ci, stem, child, child.name, [])
                continue
            self._walk_held(ci, stem, child, method, held)

    # -- queries -----------------------------------------------------------

    def cycles(self) -> List[List[LockId]]:
        """Elementary cycles in the edge set (DFS over components)."""
        graph: Dict[LockId, Set[LockId]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        out: List[List[LockId]] = []
        seen_cycles: Set[Tuple[LockId, ...]] = set()

        def dfs(start: LockId, node: LockId, stack: List[LockId]) -> None:
            for nxt in graph.get(node, ()):  # noqa: B007
                if nxt == start and len(stack) > 0:
                    canon = min(
                        tuple(stack[i:] + stack[:i])
                        for i in range(len(stack))
                    )
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(list(canon))
                elif nxt not in stack and len(stack) < 6:
                    dfs(start, nxt, stack + [nxt])

        for node in graph:
            dfs(node, node, [node])
        return out


def get_lock_graph(project: Project) -> LockGraph:
    """Build (or reuse) the lock graph for this project.

    Both lock rules need the same edge set; memoizing on the project
    halves the cost of the most expensive analysis pass."""
    cached = getattr(project, "_graftlint_lockgraph", None)
    if cached is None:
        cached = LockGraph(project)
        project._graftlint_lockgraph = cached  # type: ignore[attr-defined]
    return cached


@register
class LockOrderRule(Rule):
    name = "lock-order"
    doc = (
        "the static lock acquisition graph must be acyclic; a cycle "
        "is a potential ABBA deadlock between threads."
    )

    def check(self, project: Project) -> List[Finding]:
        graph = get_lock_graph(project)
        out: List[Finding] = []
        for cycle in graph.cycles():
            # find a witness edge on the cycle for location info
            witness = None
            for i in range(len(cycle)):
                key = (cycle[i], cycle[(i + 1) % len(cycle)])
                if key in graph.edges:
                    witness = graph.edges[key]
                    break
            desc = " -> ".join(f"{c[0]}.{c[1]}" for c in cycle)
            out.append(
                Finding(
                    rule=self.name,
                    path=witness.path if witness else "<project>",
                    line=witness.line if witness else 1,
                    message=(
                        f"lock-order cycle {desc} -> "
                        f"{cycle[0][0]}.{cycle[0][1]}: potential ABBA "
                        "deadlock (or document + refactor the nesting)"
                    ),
                )
            )
        return out


@register
class UnlockedWriteRule(Rule):
    name = "unlocked-write"
    doc = (
        "an attribute stored under a lock in one method must not be "
        "stored without it in another branch/method (minus __init__): "
        "the unlocked store races the locked readers."
    )

    def check(self, project: Project) -> List[Finding]:
        graph = get_lock_graph(project)
        out: List[Finding] = []
        for ci in graph.classes.values():
            if not ci.lock_attrs:
                continue
            locked_writes = self._writes(ci, under_lock=True, graph=graph)
            if not locked_writes:
                continue
            for attr, site in self._writes(
                ci, under_lock=False, graph=graph
            ).items():
                if attr in locked_writes:
                    out.append(
                        Finding(
                            rule=self.name,
                            path=ci.path,
                            line=site,
                            message=(
                                f"self.{attr} is written under "
                                f"{ci.name}'s lock elsewhere but "
                                "written here without it; the "
                                "unlocked store races the locked "
                                "readers/writers"
                            ),
                        )
                    )
        return out

    def _writes(
        self, ci: ClassInfo, under_lock: bool, graph: LockGraph
    ) -> Dict[str, int]:
        """attr → first write line, filtered by lock context."""
        found: Dict[str, int] = {}

        def visit(node: ast.AST, held: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.With):
                    acquires = any(
                        graph._lock_of_withitem(
                            ci, ci.module, item.context_expr
                        )
                        is not None
                        for item in child.items
                    )
                    for sub in child.body:
                        visit(sub, held or acquires)
                    continue
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (
                        child.targets
                        if isinstance(child, ast.Assign)
                        else [child.target]
                    )
                    for t in targets:
                        base = t
                        while isinstance(base, ast.Subscript):
                            base = base.value
                        if (
                            isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"
                            and held == under_lock
                            and base.attr not in found
                        ):
                            found[base.attr] = child.lineno
                visit(child, held)

        for mname, meth in ci.methods.items():
            if mname == "__init__":
                continue
            visit(meth, False)
        return found
