"""Raft message schemas (reference: raft/raft_rpc.go:3-95).

The reference carries several dead fields (``Entry.Id``,
``AppendEntriesReply.Conflict``, ``RequestVoteReply.State``,
``ClientMessageArgs/Reply`` — raft/raft_rpc.go:43,65,81,46-53); they are
deliberately not reproduced.  These dataclasses are also the wire schema
the batched engine packs into dense ``(groups, peers)`` tensors — every
field here is either a small integer (device-resident) or an opaque
payload (host-resident), and the split is annotated per message.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, List, Optional

from ..transport import codec


class Role(enum.IntEnum):
    """Peer role (reference: raft/raft_rpc.go state enums)."""

    FOLLOWER = 0
    CANDIDATE = 1
    LEADER = 2


@codec.registered
@dataclasses.dataclass
class Entry:
    """One log entry.  ``index``/``term`` live on device in the batched
    engine; ``command`` stays host-side keyed by (group, index)."""

    index: int = 0
    term: int = 0
    command: Any = None


@codec.registered
@dataclasses.dataclass
class ApplyMsg:
    """Commit notification to the service layer
    (reference: raft/raft_rpc.go:26-41)."""

    command_valid: bool = False
    command: Any = None
    command_index: int = 0
    command_term: int = 0

    snapshot_valid: bool = False
    snapshot: Any = None
    snapshot_index: int = 0
    snapshot_term: int = 0


@codec.registered
@dataclasses.dataclass
class RequestVoteArgs:
    """(reference: raft/raft_rpc.go RequestVote args)"""

    term: int = 0
    candidate_id: int = -1
    last_log_index: int = 0
    last_log_term: int = 0
    # Non-binding PreVote probe (opt-in; see RaftNode(prevote=True)):
    # ``term`` then carries the PROPOSED term (candidate's term + 1).
    pre: bool = False


@codec.registered
@dataclasses.dataclass
class RequestVoteReply:
    term: int = 0
    vote_granted: bool = False


@codec.registered
@dataclasses.dataclass
class AppendEntriesArgs:
    """(reference: raft/raft_rpc.go AppendEntries args).  In the batched
    engine this becomes a fixed-width record: entries are (start, count)
    plus a terms slice of max width E."""

    term: int = 0
    leader_id: int = -1
    prev_log_index: int = 0
    prev_log_term: int = 0
    entries: List[Entry] = dataclasses.field(default_factory=list)
    leader_commit: int = 0


@codec.registered
@dataclasses.dataclass
class AppendEntriesReply:
    """``conflict_index`` implements the term-skipping fast backup
    (reference: raft/raft_append_entry.go:136-143).  Divergence from the
    reference, documented: when ``prev_log_index`` falls below the
    follower's snapshot base the reference replies Term=0 (quirk;
    raft/raft_append_entry.go:123-127) — we reply with the real term and
    ``conflict_index = base + 1``."""

    term: int = 0
    success: bool = False
    conflict_index: int = 0


@codec.registered
@dataclasses.dataclass
class InstallSnapshotArgs:
    """(reference: raft/raft_rpc.go InstallSnapshot args).  ``data`` is
    the service snapshot blob — host-side in the batched engine."""

    term: int = 0
    leader_id: int = -1
    last_included_index: int = 0
    last_included_term: int = 0
    data: Any = None


@codec.registered
@dataclasses.dataclass
class InstallSnapshotReply:
    term: int = 0


@codec.registered
@dataclasses.dataclass
class PersistentState:
    """What survives a crash (reference: raft/raft.go:205-235): term,
    vote, and the full log including the dummy head entry that carries
    (last_snapshot_index, last_snapshot_term)."""

    current_term: int = 0
    voted_for: Optional[int] = None
    entries: List[Entry] = dataclasses.field(default_factory=list)
