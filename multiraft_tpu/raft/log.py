"""In-memory Raft log with snapshot rebase (reference: raft/raft_log.go).

Entry 0 is a dummy carrying ``(last_snapshot_index, last_snapshot_term)``
(reference: raft/raft_log.go:3-5); all absolute indices are translated
through the base (``convertIndex``, raft/raft_log.go:55-60).  This is the
Python mirror of the batched engine's fixed-capacity device ring +
``log_base`` arithmetic — same index algebra, dynamic storage.
"""

from __future__ import annotations

from typing import List, Optional

from .messages import Entry

__all__ = ["RaftLog"]


class RaftLog:
    def __init__(self, entries: Optional[List[Entry]] = None) -> None:
        # entries[0] is always the dummy: index = snapshot index,
        # term = snapshot term, command = None.
        self.entries: List[Entry] = entries or [Entry(index=0, term=0)]

    # -- bounds -----------------------------------------------------------

    @property
    def base(self) -> int:
        """Index of the dummy head == last snapshot index."""
        return self.entries[0].index

    @property
    def base_term(self) -> int:
        return self.entries[0].term

    @property
    def last_index(self) -> int:
        return self.entries[-1].index

    @property
    def last_term(self) -> int:
        return self.entries[-1].term

    def __len__(self) -> int:
        """Number of real entries (excluding the dummy)."""
        return len(self.entries) - 1

    # -- access -----------------------------------------------------------

    def _pos(self, index: int) -> int:
        """Absolute index → list position (convertIndex,
        reference: raft/raft_log.go:55-60)."""
        pos = index - self.base
        if pos < 0 or pos >= len(self.entries):
            raise IndexError(
                f"log index {index} out of range [base={self.base}, "
                f"last={self.last_index}]"
            )
        return pos

    def at(self, index: int) -> Entry:
        return self.entries[self._pos(index)]

    def term_at(self, index: int) -> int:
        return self.entries[self._pos(index)].term

    def has(self, index: int) -> bool:
        return self.base <= index <= self.last_index

    def slice_from(self, index: int) -> List[Entry]:
        """Entries with absolute index ≥ ``index``
        (reference: raft/raft_log.go sliceFrom)."""
        return self.entries[self._pos(index):] if index <= self.last_index else []

    # -- mutation ---------------------------------------------------------

    def append(self, entry: Entry) -> None:
        entry.index = self.last_index + 1
        # The replicated log grows by design; snapshot compaction
        # (compact_to, driven by maxraftstate) is what bounds it.
        self.entries.append(entry)  # graftlint: disable=unbounded-queue

    def truncate_from(self, index: int) -> None:
        """Drop entries with absolute index ≥ ``index``
        (reference: raft/raft_log.go trunc)."""
        del self.entries[self._pos(index):]

    def compact_to(self, index: int, term: Optional[int] = None) -> None:
        """Discard entries ≤ ``index``, installing a new dummy head —
        snapshot rebase (reference: raft/raft_snapshot.go:10-12).

        If ``index`` is beyond the log (InstallSnapshot ahead of us),
        ``term`` supplies the dummy's term and the log empties."""
        if index <= self.base:
            return
        if self.has(index):
            keep = self.entries[self._pos(index):]
            keep[0] = Entry(index=index, term=keep[0].term, command=None)
            self.entries = keep
        else:
            assert term is not None, "compact beyond log needs explicit term"
            self.entries = [Entry(index=index, term=term, command=None)]

    # -- predicates -------------------------------------------------------

    def matches(self, prev_index: int, prev_term: int) -> bool:
        """Does the log contain ``prev_index`` with ``prev_term``?
        (reference: raft/raft_log.go:92-96)"""
        return self.has(prev_index) and self.term_at(prev_index) == prev_term

    def up_to_date(self, last_index: int, last_term: int) -> bool:
        """Election restriction (reference: raft/raft_log.go:99-104):
        candidate's log is at least as up-to-date as ours."""
        if last_term != self.last_term:
            return last_term > self.last_term
        return last_index >= self.last_index

    def first_index_of_term(self, term: int, from_index: int) -> int:
        """Scan back from ``from_index`` to the first entry of ``term`` —
        the conflict fast-backup scan
        (reference: raft/raft_append_entry.go:136-143)."""
        i = from_index
        while i - 1 > self.base and self.term_at(i - 1) == term:
            i -= 1
        return i
