"""Event-driven single-group Raft — the framework's correctness oracle.

The reference runs each peer as goroutine families: a ticker, per-peer
replicators, and an applier (reference: raft/raft.go:51-87,106-203).
This implementation inverts that into pure event handlers on the
virtual-time scheduler: timers are scheduled events, RPC replies are
future callbacks, and apply is a drained queue — zero locks, fully
deterministic, and structurally identical to one lane of the batched
TPU engine's tick function (see ``multiraft_tpu.engine``), which is
golden-tested against this class by the differential conformance rig
(``multiraft_tpu/conformance.py`` + ``tests/test_conformance.py``:
identical seeded fault scenarios on both backends must commit
identical command streams).

Protocol semantics follow the reference:

* election and vote-granting rules (reference: raft/raft_election.go)
* heartbeat-as-repair: every heartbeat is a full AppendEntries carrying
  any missing suffix (reference: raft/raft_append_entry.go:9-12,44-55)
* conflict-index fast backup (reference: raft/raft_append_entry.go:136-143)
* quorum commit advance with the current-term guard
  (reference: raft/raft_append_entry.go:89-105)
* out-of-order/duplicate RPC tolerance: no truncation on stale prefixes
  (reference: raft/raft_append_entry.go:146-155), staleness guard on
  replies (reference: raft/raft_append_entry.go:74)
* service-driven snapshots + InstallSnapshot with commit fast-forward and
  the apply-ordering guarantee (reference: raft/raft_snapshot.go)

Documented divergences from reference quirks (SURVEY §7.5): fresh RNG
per timeout is replaced by one seeded per-node RNG; the Term=0 reply
quirk is fixed; ``CondInstallSnapshot`` (a constant-true vestige) is not
reproduced.
"""

from __future__ import annotations

import random
from typing import Any, Callable, List, Optional

from ..sim.scheduler import Scheduler
from ..transport import codec
from ..transport.network import ClientEnd
from .log import RaftLog
from .messages import (
    AppendEntriesArgs,
    AppendEntriesReply,
    ApplyMsg,
    Entry,
    InstallSnapshotArgs,
    InstallSnapshotReply,
    PersistentState,
    RequestVoteArgs,
    RequestVoteReply,
    Role,
)
from .persister import Persister
from ..distributed import flightrec
from ..utils.metrics import trace

__all__ = ["RaftNode", "HEARTBEAT_INTERVAL", "ELECTION_TIMEOUT"]

# Timing (reference: raft/raft.go:42-50), in virtual seconds — read
# from the config system (utils/config.py), overridable via
# MULTIRAFT_HEARTBEAT / MULTIRAFT_ELECTION_MIN / _MAX.
from ..utils.config import settings as _settings

HEARTBEAT_INTERVAL = _settings().raft.heartbeat
ELECTION_TIMEOUT = _settings().raft.election


class RaftNode:
    """One Raft peer.  RPC handler methods (``request_vote``,
    ``append_entries``, ``install_snapshot``) are dispatched by the
    simulated network under service name ``"Raft"``."""

    def __init__(
        self,
        sched: Scheduler,
        peers: List[ClientEnd],
        me: int,
        persister: Persister,
        apply_fn: Callable[[ApplyMsg], None],
        seed: int = 0,
        prevote: bool = False,
    ) -> None:
        self.sched = sched
        self.peers = peers
        self.me = me
        self.persister = persister
        self.apply_fn = apply_fn
        self.rng = random.Random((seed << 16) ^ me)
        # PreVote (etcd/TiKV-style, beyond the reference): election
        # timeouts probe with a non-binding prevote round first; see
        # the engine's EngineConfig.prevote for the design notes.
        self.prevote = prevote
        self._last_heard = float("-inf")  # time a leader was last heard

        self.current_term = 0
        self.voted_for: Optional[int] = None
        self.log = RaftLog()
        self.role = Role.FOLLOWER
        self.commit_index = 0
        self.last_applied = 0
        self.next_index = [1] * len(peers)
        self.match_index = [0] * len(peers)
        self._killed = False

        # Replicator coalescing state (reference: raft/raft.go:134-150 —
        # one replicator goroutine per peer parking on a cond var).
        self._in_flight = [False] * len(peers)
        self._pending = [False] * len(peers)

        # Pending snapshot to surface on the apply path before newer
        # entries (reference: raft/raft.go:168-177).
        self._pending_snapshot: Optional[ApplyMsg] = None
        self._apply_scheduled = False

        self._election_timer = None
        self._heartbeat_timer = None

        # Black box (flightrec.py): role/term/commit transitions in the
        # crash-surviving ring.  None when MRT_FLIGHTREC_DIR is unset —
        # the sim suites pay one `is None` check per transition.
        self._frec = flightrec.get_recorder()

        self._read_persist()
        self.commit_index = self.log.base
        self.last_applied = self.log.base
        self._reset_election_timer()

    # ------------------------------------------------------------------
    # Public API (reference: raft/raft.go:51,90,237; raft/raft_snapshot.go:3)
    # ------------------------------------------------------------------

    def start(self, command: Any) -> tuple[int, int, bool]:
        """Propose a command (reference: raft/raft.go:90-104).  Returns
        (index, term, is_leader); replication begins immediately."""
        if self._killed or self.role != Role.LEADER:
            return -1, self.current_term, False
        entry = Entry(term=self.current_term, command=command)
        self.log.append(entry)
        self.match_index[self.me] = self.log.last_index
        self._persist()
        if len(self.peers) == 1:
            self._advance_commit()
        else:
            for p in range(len(self.peers)):
                if p != self.me:
                    self._kick_replicator(p)
        return entry.index, self.current_term, True

    def get_state(self) -> tuple[int, bool]:
        return self.current_term, self.role == Role.LEADER

    def kill(self) -> None:
        """(reference: raft/utility.go:9-24)"""
        self._killed = True
        if self._election_timer:
            self._election_timer.cancel()
        if self._heartbeat_timer:
            self._heartbeat_timer.cancel()

    def killed(self) -> bool:
        return self._killed

    def snapshot(self, index: int, snapshot: bytes) -> None:
        """Service-driven log compaction (reference: raft/raft_snapshot.go:3-13):
        the service has serialized its state through ``index``; discard
        entries ≤ index and persist the pair atomically."""
        if self._killed or index <= self.log.base:
            return
        self.log.compact_to(index)
        self.persister.save_state_and_snapshot(self._encode_state(), snapshot)

    def raft_state_size(self) -> int:
        return self.persister.raft_state_size()

    def read_snapshot(self) -> bytes:
        return self.persister.read_snapshot()

    # ------------------------------------------------------------------
    # Persistence (reference: raft/raft.go:205-235)
    # ------------------------------------------------------------------

    def _encode_state(self) -> bytes:
        return codec.encode(
            PersistentState(
                current_term=self.current_term,
                voted_for=self.voted_for,
                entries=self.log.entries,
            )
        )

    def _persist(self) -> None:
        # Full-state re-persist on every mutation, as the reference does
        # (quirk #6, raft/raft.go:205-216); the snapshot blob is carried
        # forward so the pair stays consistent.
        snap = self.persister.read_snapshot()
        if snap:
            self.persister.save_state_and_snapshot(self._encode_state(), snap)
        else:
            self.persister.save_raft_state(self._encode_state())

    def _read_persist(self) -> None:
        data = self.persister.read_raft_state()
        if not data:
            return
        st: PersistentState = codec.decode(data)
        self.current_term = st.current_term
        self.voted_for = st.voted_for
        self.log = RaftLog(st.entries)

    # ------------------------------------------------------------------
    # Timers (reference: raft/raft.go:106-125 ticker)
    # ------------------------------------------------------------------

    def _reset_election_timer(self) -> None:
        if self._election_timer:
            self._election_timer.cancel()
        timeout = self.rng.uniform(*ELECTION_TIMEOUT)
        self._election_timer = self.sched.call_after(
            timeout, self._on_election_timeout
        )

    def _on_election_timeout(self) -> None:
        if self._killed:
            return
        if self.role != Role.LEADER:
            if self.prevote:
                self._start_prevote()
            else:
                self._start_election()
        self._reset_election_timer()

    def _start_prevote(self) -> None:
        """Non-binding probe at term+1: no term bump, no voted_for, no
        persistence.  A quorum of grants (self included) launches the
        real election; hearing a leader mid-round aborts it."""
        term = self.current_term
        started = self.sched.now
        granted = [1]
        if self._quorum(granted[0]):
            self._start_election()
            return
        args = RequestVoteArgs(
            term=term + 1,
            candidate_id=self.me,
            last_log_index=self.log.last_index,
            last_log_term=self.log.last_term,
            pre=True,
        )
        for p in range(len(self.peers)):
            if p == self.me:
                continue
            fut = self.peers[p].call("Raft.request_vote", args)
            fut.add_done_callback(
                lambda f, _t=term, _s=started, _g=granted: (
                    self._on_prevote_reply(_t, _s, _g, f.value)
                )
            )

    def _on_prevote_reply(
        self,
        term: int,
        started: float,
        granted: list,
        reply: Optional[RequestVoteReply],
    ) -> None:
        if self._killed or reply is None:
            return
        if reply.term > self.current_term:
            self._step_down(reply.term)
            return
        # Round still current?  Same term, still not leader, and no
        # leader heard since the round began (an accepted append aborts
        # the campaign, as etcd does on MsgApp/MsgHeartbeat).
        if (
            self.role == Role.LEADER
            or self.current_term != term
            or self._last_heard >= started
        ):
            return
        if reply.vote_granted:
            granted[0] += 1
            if self._quorum(granted[0]):
                self._start_election()

    def _start_heartbeats(self) -> None:
        if self._heartbeat_timer:
            self._heartbeat_timer.cancel()
        self._heartbeat_timer = self.sched.call_after(
            HEARTBEAT_INTERVAL, self._on_heartbeat
        )

    def _on_heartbeat(self) -> None:
        if self._killed or self.role != Role.LEADER:
            return
        self._broadcast_heartbeat()
        self._start_heartbeats()

    # ------------------------------------------------------------------
    # Election (reference: raft/raft_election.go)
    # ------------------------------------------------------------------

    def _record_role(self) -> None:
        """Flight-recorder hook: one fixed-width record per role/term
        transition (no-op when recording is disabled)."""
        fr = self._frec
        if fr is not None:
            fr.record(
                flightrec.ROLE, code=self.me, a=int(self.role),
                b=self.current_term, c=self.commit_index,
            )

    def _start_election(self) -> None:
        """(reference: raft/raft_election.go:4-51)"""
        self.role = Role.CANDIDATE
        self.current_term += 1
        self.voted_for = self.me
        self._record_role()
        self._persist()
        term = self.current_term
        granted = [1]  # own vote; list for closure mutation
        if self._quorum(granted[0]):
            self._become_leader()
            return
        args = RequestVoteArgs(
            term=term,
            candidate_id=self.me,
            last_log_index=self.log.last_index,
            last_log_term=self.log.last_term,
        )
        for p in range(len(self.peers)):
            if p == self.me:
                continue
            fut = self.peers[p].call("Raft.request_vote", args)
            fut.add_done_callback(
                lambda f, _term=term, _g=granted: self._on_vote_reply(
                    _term, _g, f.value
                )
            )

    def _on_vote_reply(
        self, term: int, granted: list, reply: Optional[RequestVoteReply]
    ) -> None:
        """(reference: raft/raft_election.go:27-49 closure)"""
        if self._killed or reply is None:
            return
        if reply.term > self.current_term:
            self._step_down(reply.term)
            return
        # Staleness guards: still the same candidacy?
        if self.role != Role.CANDIDATE or self.current_term != term:
            return
        if reply.vote_granted:
            granted[0] += 1
            if self._quorum(granted[0]):
                self._become_leader()

    def _quorum(self, n: int) -> bool:
        return n > len(self.peers) // 2

    def _become_leader(self) -> None:
        """(reference: raft/raft_election.go:30-41)"""
        trace("raft %d: leader at term %d", self.me, self.current_term)
        self.role = Role.LEADER
        self._record_role()
        last = self.log.last_index
        for p in range(len(self.peers)):
            self.next_index[p] = last + 1
            self.match_index[p] = 0
        self.match_index[self.me] = last
        self._broadcast_heartbeat()
        self._start_heartbeats()

    def _step_down(self, term: int) -> None:
        changed = term > self.current_term
        if changed and self.role is not Role.FOLLOWER:
            trace("raft %d: step down %d -> %d", self.me,
                  self.current_term, term)
        was_follower = self.role is Role.FOLLOWER
        self.current_term = max(self.current_term, term)
        if changed:
            self.voted_for = None
        self.role = Role.FOLLOWER
        if changed or not was_follower:
            self._record_role()
        if self._heartbeat_timer:
            self._heartbeat_timer.cancel()
        if changed:
            self._persist()

    def request_vote(self, args: RequestVoteArgs) -> RequestVoteReply:
        """RPC handler (reference: raft/raft_election.go:54-77).  A
        ``pre`` probe is non-binding: grant iff the proposed term would
        win, the log is up to date, and this voter is out of lease —
        never while leading, never after hearing a leader within the
        minimum election timeout."""
        if args.pre:
            grant = (
                self.role != Role.LEADER
                and args.term > self.current_term
                and (self.sched.now - self._last_heard) >= ELECTION_TIMEOUT[0]
                and self.log.up_to_date(args.last_log_index, args.last_log_term)
            )
            return RequestVoteReply(term=self.current_term, vote_granted=grant)
        if args.term > self.current_term:
            self._step_down(args.term)
        if args.term < self.current_term:
            return RequestVoteReply(term=self.current_term, vote_granted=False)
        grant = self.voted_for in (None, args.candidate_id) and self.log.up_to_date(
            args.last_log_index, args.last_log_term
        )
        if grant:
            self.voted_for = args.candidate_id
            self._persist()
            self._reset_election_timer()
            self._last_heard = self.sched.now
        return RequestVoteReply(term=self.current_term, vote_granted=grant)

    # ------------------------------------------------------------------
    # Replication (reference: raft/raft_append_entry.go)
    # ------------------------------------------------------------------

    def _broadcast_heartbeat(self) -> None:
        """Heartbeats bypass the replicator coalescing and fire
        immediately (reference: raft/raft_append_entry.go:9-12); the
        reply staleness guard tolerates the resulting concurrency."""
        for p in range(len(self.peers)):
            if p != self.me:
                self._append_one_round(p)

    def _kick_replicator(self, peer: int) -> None:
        """Coalesce bursts of Start() into one RPC per peer — the
        replicator-thread pattern (reference: raft/raft.go:134-150)."""
        if self._in_flight[peer]:
            self._pending[peer] = True
        else:
            self._append_one_round(peer)

    def _append_one_round(self, peer: int) -> None:
        """(reference: raft/raft_append_entry.go:20-65)"""
        if self._killed or self.role != Role.LEADER:
            return
        ni = self.next_index[peer]
        if ni - 1 < self.log.base:
            self._send_install_snapshot(peer)
            return
        args = AppendEntriesArgs(
            term=self.current_term,
            leader_id=self.me,
            prev_log_index=ni - 1,
            prev_log_term=self.log.term_at(ni - 1),
            entries=self.log.slice_from(ni) if ni <= self.log.last_index else [],
            leader_commit=self.commit_index,
        )
        self._in_flight[peer] = True
        fut = self.peers[peer].call("Raft.append_entries", args)
        fut.add_done_callback(
            lambda f, _a=args: self._on_append_reply(peer, _a, f.value)
        )

    def _on_append_reply(
        self,
        peer: int,
        args: AppendEntriesArgs,
        reply: Optional[AppendEntriesReply],
    ) -> None:
        """(reference: raft/raft_append_entry.go:66-88)"""
        self._in_flight[peer] = False
        if self._killed:
            return
        if reply is not None and reply.term > self.current_term:
            self._step_down(reply.term)
            return
        if self.role != Role.LEADER or self.current_term != args.term:
            return
        if reply is not None:
            if reply.success:
                match = args.prev_log_index + len(args.entries)
                if match > self.match_index[peer]:
                    self.match_index[peer] = match
                    self.next_index[peer] = match + 1
                    self._advance_commit()
            elif args.prev_log_index == self.next_index[peer] - 1:
                # Staleness guard (reference: raft/raft_append_entry.go:74):
                # only back off if this reply answers the current round.
                self.next_index[peer] = max(1, reply.conflict_index)
                self._pending[peer] = True
        if self._pending[peer]:
            self._pending[peer] = False
            self._append_one_round(peer)

    def _advance_commit(self) -> None:
        """Quorum-median commit advance with the current-term guard
        (reference: raft/raft_append_entry.go:89-105).  This scan *is*
        the north-star batched kernel: per-group median of match_index."""
        for i in range(self.log.last_index, self.commit_index, -1):
            if self.log.term_at(i) != self.current_term:
                break  # only current-term entries commit by counting
            count = sum(1 for p in range(len(self.peers)) if self.match_index[p] >= i)
            if self._quorum(count):
                self.commit_index = i
                self._schedule_apply()
                break

    def append_entries(self, args: AppendEntriesArgs) -> AppendEntriesReply:
        """RPC handler (reference: raft/raft_append_entry.go:108-162)."""
        if args.term < self.current_term:
            return AppendEntriesReply(term=self.current_term, success=False)
        self._step_down(args.term)
        self._reset_election_timer()
        self._last_heard = self.sched.now  # lease: a live leader spoke

        if args.prev_log_index < self.log.base:
            # Our snapshot already covers prev; tell the leader where we
            # begin (divergence from the Term=0 quirk, SURVEY §7.5 #5).
            return AppendEntriesReply(
                term=self.current_term,
                success=False,
                conflict_index=self.log.base + 1,
            )
        if not self.log.matches(args.prev_log_index, args.prev_log_term):
            # Conflict fast-backup (reference: raft/raft_append_entry.go:136-143).
            if args.prev_log_index > self.log.last_index:
                ci = self.log.last_index + 1
            else:
                ci = self.log.first_index_of_term(
                    self.log.term_at(args.prev_log_index), args.prev_log_index
                )
            return AppendEntriesReply(
                term=self.current_term, success=False, conflict_index=ci
            )

        # Append entries, truncating only at a genuine conflict so
        # duplicated/reordered messages are harmless
        # (reference: raft/raft_append_entry.go:146-155).
        changed = False
        for entry in args.entries:
            if entry.index <= self.log.base:
                continue
            if self.log.has(entry.index):
                if self.log.term_at(entry.index) == entry.term:
                    continue
                self.log.truncate_from(entry.index)
                changed = True
            # Replicated log: bounded by snapshot compaction, not here.
            self.log.entries.append(entry)  # graftlint: disable=unbounded-queue
            changed = True
        if changed:
            self._persist()

        # Follower commit advance
        # (reference: raft/raft_append_entry.go:157-160).
        upper = args.prev_log_index + len(args.entries)
        if args.leader_commit > self.commit_index:
            new_commit = min(args.leader_commit, upper)
            if new_commit > self.commit_index:
                self.commit_index = new_commit
                self._schedule_apply()
        return AppendEntriesReply(term=self.current_term, success=True)

    # ------------------------------------------------------------------
    # Snapshots (reference: raft/raft_snapshot.go)
    # ------------------------------------------------------------------

    def _send_install_snapshot(self, peer: int) -> None:
        """(reference: raft/raft_append_entry.go:27-39 +
        raft/raft_snapshot.go:56-69)"""
        args = InstallSnapshotArgs(
            term=self.current_term,
            leader_id=self.me,
            last_included_index=self.log.base,
            last_included_term=self.log.base_term,
            data=self.persister.read_snapshot(),
        )
        self._in_flight[peer] = True
        fut = self.peers[peer].call("Raft.install_snapshot", args)

        def on_reply(f, _a=args):
            self._in_flight[peer] = False
            reply: Optional[InstallSnapshotReply] = f.value
            if self._killed or reply is None:
                return
            if reply.term > self.current_term:
                self._step_down(reply.term)
                return
            if self.role != Role.LEADER or self.current_term != _a.term:
                return
            if _a.last_included_index > self.match_index[peer]:
                self.match_index[peer] = _a.last_included_index
                self.next_index[peer] = _a.last_included_index + 1
            if self._pending[peer]:
                self._pending[peer] = False
                self._append_one_round(peer)

        fut.add_done_callback(on_reply)

    def install_snapshot(self, args: InstallSnapshotArgs) -> InstallSnapshotReply:
        """RPC handler (reference: raft/raft_snapshot.go:15-54)."""
        if args.term < self.current_term:
            return InstallSnapshotReply(term=self.current_term)
        self._step_down(args.term)
        self._reset_election_timer()
        self._last_heard = self.sched.now  # lease: a live leader spoke
        if args.last_included_index <= self.commit_index:
            # Already have everything the snapshot covers.
            return InstallSnapshotReply(term=self.current_term)

        if self.log.has(args.last_included_index) and self.log.term_at(
            args.last_included_index
        ) == args.last_included_term:
            self.log.compact_to(args.last_included_index)
        else:
            self.log.compact_to(
                args.last_included_index, term=args.last_included_term
            )
        # Fast-forward: everything ≤ snapshot index is, by definition,
        # committed and applied once the service installs the blob
        # (reference: raft/raft_snapshot.go:40-49).
        self.commit_index = args.last_included_index
        self.last_applied = args.last_included_index
        self.persister.save_state_and_snapshot(self._encode_state(), args.data)
        # Surface the snapshot on the apply path *before* later entries
        # (ordering guarantee, reference: raft/raft_snapshot.go:51-53).
        self._pending_snapshot = ApplyMsg(
            snapshot_valid=True,
            snapshot=args.data,
            snapshot_index=args.last_included_index,
            snapshot_term=args.last_included_term,
        )
        self._schedule_apply()
        return InstallSnapshotReply(term=self.current_term)

    # ------------------------------------------------------------------
    # Applier (reference: raft/raft.go:153-203)
    # ------------------------------------------------------------------

    def _schedule_apply(self) -> None:
        if not self._apply_scheduled:
            self._apply_scheduled = True
            self.sched.call_soon(self._apply_loop)

    def _apply_loop(self) -> None:
        self._apply_scheduled = False
        if self._killed:
            return
        if self._pending_snapshot is not None:
            msg, self._pending_snapshot = self._pending_snapshot, None
            self.apply_fn(msg)
        while self.last_applied < self.commit_index and not self._killed:
            self.last_applied += 1
            entry = self.log.at(self.last_applied)
            self.apply_fn(
                ApplyMsg(
                    command_valid=True,
                    command=entry.command,
                    command_index=entry.index,
                    command_term=entry.term,
                )
            )
            if self._pending_snapshot is not None:
                # An InstallSnapshot landed mid-apply; surface it in order.
                msg, self._pending_snapshot = self._pending_snapshot, None
                self.apply_fn(msg)

    # ------------------------------------------------------------------

    def __repr__(self) -> str:  # debug aid (GetState2, raft/utility.go:26-39)
        return (
            f"<Raft {self.me} {self.role.name} t={self.current_term} "
            f"log=[{self.log.base}..{self.log.last_index}] "
            f"commit={self.commit_index} applied={self.last_applied}>"
        )
