"""Durable-state store (reference: raft/persister.go).

In-memory byte slices with an atomic (state, snapshot) pair save and a
``copy()`` used by the crash/restart fixture to hand the reborn server
exactly what its predecessor persisted
(reference: raft/persister.go:57-64, raft/config.go:113-142).

This is the test/bench store; a real deployment plugs a durable backend
behind the same five methods.
"""

from __future__ import annotations


__all__ = ["Persister"]


class Persister:
    def __init__(self) -> None:
        self._raft_state: bytes = b""
        self._snapshot: bytes = b""

    def copy(self) -> "Persister":
        p = Persister()
        p._raft_state = self._raft_state
        p._snapshot = self._snapshot
        return p

    def save_raft_state(self, state: bytes) -> None:
        self._raft_state = state

    def read_raft_state(self) -> bytes:
        return self._raft_state

    def raft_state_size(self) -> int:
        return len(self._raft_state)

    def save_state_and_snapshot(self, state: bytes, snapshot: bytes) -> None:
        """Atomic pair save so the service snapshot can never run ahead of
        the raft state it corresponds to (reference: raft/persister.go:57-64)."""
        self._raft_state = state
        self._snapshot = snapshot

    def read_snapshot(self) -> bytes:
        return self._snapshot

    def snapshot_size(self) -> int:
        return len(self._snapshot)
