"""Command-line entry: serve engines and talk to them.

The reference ships as a Go library driven by `go test`; this
framework additionally deploys.  The CLI wraps the server entrypoints
(`distributed.engine_server`) and a one-shot client so an operator can
stand up a chip-owning KV service and poke it without writing code:

    python -m multiraft_tpu serve-kv --port 7000 --groups 64 \
        --data-dir /var/lib/mrt --platform tpu
    python -m multiraft_tpu kv put  --addr 127.0.0.1:7000 greeting hello
    python -m multiraft_tpu kv get  --addr 127.0.0.1:7000 greeting

Sharded/fleet serving uses the same flags plus --gids/--peer; process
supervision (restart-on-crash, placement) belongs to the operator's
init system — a restarted durable server recovers from --data-dir.
"""

from __future__ import annotations

import argparse
import sys


def _pin(platform: str) -> None:
    import jax

    try:
        jax.config.update("jax_platforms", platform)
    except Exception as exc:
        if platform != "cpu":
            raise RuntimeError(f"could not pin platform {platform}: {exc}")


def _serve_forever(args, build) -> int:
    """Shared serve scaffold: pin the backend, build the node, print
    the readiness line, park the main thread.

    SIGTERM/SIGINT shut down gracefully: a durable server writes a
    final checkpoint (rotating the WAL away), so the next start
    recovers instantly instead of replaying — kill -9 remains the
    crash path and recovers via WAL replay."""
    import signal
    import threading

    _pin(args.platform)
    node = build()
    stop = threading.Event()

    def _on_signal(*_):
        if stop.is_set():
            # Second signal: the graceful path is wedged (e.g. a stalled
            # device mid-checkpoint) — force-exit like the pre-handler
            # behavior instead of sitting out the run_call timeout.
            import os as _os

            _os._exit(130)
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)
    print(f"ready {node.port}", flush=True)
    stop.wait()
    svc = getattr(node, "engine_service", None)
    if svc is not None:
        # On the loop thread: checkpoint at a tick boundary, not mid-pump.
        node.sched.run_call(svc.final_checkpoint, timeout=600.0)
    node.close()
    return 0


def _cmd_serve_kv(args) -> int:
    def build():
        from .distributed.engine_server import serve_engine_kv

        return serve_engine_kv(
            port=args.port,
            G=args.groups,
            host=args.host,
            seed=args.seed,
            data_dir=args.data_dir,
            checkpoint_every_s=args.checkpoint_every,
            mesh_devices=args.mesh_devices,
        )

    return _serve_forever(args, build)


def _cmd_serve_shardkv(args) -> int:
    def build():
        from .distributed.engine_server import serve_engine_shardkv

        peer_addrs = {}
        for spec in args.peer or []:
            gid, addr = spec.split("=", 1)
            h, p = addr.rsplit(":", 1)
            peer_addrs[int(gid)] = (h, int(p))
        gids = [int(g) for g in args.gids.split(",")] if args.gids else None
        return serve_engine_shardkv(
            port=args.port,
            G=args.groups,
            host=args.host,
            seed=args.seed,
            join_gids=(
                [int(g) for g in args.join.split(",")] if args.join else None
            ),
            gids=gids,
            peer_addrs=peer_addrs or None,
            data_dir=args.data_dir,
            checkpoint_every_s=args.checkpoint_every,
            mesh_devices=args.mesh_devices,
        )

    return _serve_forever(args, build)


def _cmd_kv(args) -> int:
    from .distributed.engine_server import EngineClerk
    from .distributed.tcp import RpcNode
    from .sim.scheduler import TIMEOUT

    if args.op != "get" and args.value is None:
        # Silently writing "" on a forgotten value would be data
        # destruction with exit code 0.
        print(f"error: kv {args.op} requires a VALUE", file=sys.stderr)
        return 2
    h, p = args.addr.rsplit(":", 1)
    node = RpcNode()
    try:
        end = node.client_end(h, int(p))
        ck = EngineClerk(node.sched, end, service=args.service)
        if args.op == "get":
            gen = ck.get(args.key)
        elif args.op == "put":
            gen = ck.put(args.key, args.value)
        else:
            gen = ck.append(args.key, args.value)
        out = node.sched.wait(node.sched.spawn(gen), args.timeout)
        if out is TIMEOUT:
            print("error: server did not answer", file=sys.stderr)
            return 1
        if args.op == "get":
            print(out)
        return 0
    finally:
        node.close()


def _add_serve_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = ephemeral, printed on ready)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--groups", type=int, default=64,
                   help="engine consensus groups (G)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--data-dir", default=None,
                   help="enable durability (checkpoints + WAL) here")
    p.add_argument("--checkpoint-every", type=float, default=30.0,
                   metavar="SECONDS")
    p.add_argument("--mesh-devices", type=int, default=0,
                   help="run the tick over this many local chips")
    p.add_argument("--platform", default="cpu", choices=("cpu", "tpu"),
                   help="pin the jax backend (tpu = own the chip)")


def main(argv=None) -> int:
    top = argparse.ArgumentParser(prog="multiraft_tpu", description=__doc__)
    sub = top.add_subparsers(dest="cmd", required=True)

    s1 = sub.add_parser("serve-kv", help="chip-owning engine KV server")
    _add_serve_flags(s1)
    s1.set_defaults(fn=_cmd_serve_kv)

    s2 = sub.add_parser("serve-shardkv",
                        help="sharded engine server (standalone or fleet)")
    _add_serve_flags(s2)
    s2.add_argument("--join", default=None, metavar="GID,GID",
                    help="bootstrap-join these gids before readiness")
    s2.add_argument("--gids", default=None, metavar="GID,GID",
                    help="fleet mode: the global gids THIS process hosts")
    s2.add_argument("--peer", action="append", metavar="GID=HOST:PORT",
                    help="fleet mode: owner address of a remote gid")
    s2.set_defaults(fn=_cmd_serve_shardkv)

    s3 = sub.add_parser("kv", help="one-shot client op")
    s3.add_argument("op", choices=("get", "put", "append"))
    s3.add_argument("key")
    s3.add_argument("value", nargs="?", default=None)
    s3.add_argument("--addr", required=True, metavar="HOST:PORT")
    s3.add_argument("--service", default="EngineKV",
                    choices=("EngineKV", "EngineShardKV"))
    s3.add_argument("--timeout", type=float, default=30.0)
    s3.set_defaults(fn=_cmd_kv)

    args = top.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
