"""Sharded KV: a shard controller plus two replica groups, with live
shard migration on join/leave and data carried across owners.

(Reference analog: shardkv/test_test.go TestJoinLeave — the behavior
the reference's server skeleton left unimplemented, built here in
full.)
"""

import sys, os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.harness.shardkv_harness import ShardKVHarness
from multiraft_tpu.services.shardctrler import NSHARDS
from multiraft_tpu.services.shardkv import key2shard


def main() -> None:
    cfg = ShardKVHarness(n=3, ngroups=2, seed=3)
    ck = cfg.make_client()

    cfg.join(100)
    cfg.sched.run_for(1.0)
    keys = [str(i) for i in range(NSHARDS)]
    for k in keys:
        cfg.run(ck.put(k, "v" + k))
    conf = cfg.run(cfg.ctl_ck.query(-1))
    print(f"group 100 owns all {NSHARDS} shards: {list(conf.shards)}")

    cfg.join(101)
    cfg.sched.run_for(2.0)  # migration runs in the background
    conf = cfg.run(cfg.ctl_ck.query(-1))
    moved = [s for s in range(NSHARDS) if conf.shards[s] == 101]
    print(f"after join(101), shards {moved} migrated (balance ±1)")
    for k in keys:
        assert cfg.run(ck.get(k)) == "v" + k, f"key {k} lost in migration"
    print("all keys survived the migration, including on the new owner")

    cfg.leave(100)
    cfg.sched.run_for(2.0)
    conf = cfg.run(cfg.ctl_ck.query(-1))
    assert all(g == 101 for g in conf.shards)
    for k in keys:
        assert cfg.run(ck.get(k)) == "v" + k
    print(f"after leave(100), group 101 serves everything "
          f"(key '3' routes via shard {key2shard('3')})")
    print("OK")


if __name__ == "__main__":
    main()
