"""Durable engine serving: kill -9 the chip owner, lose nothing.

The batched engine can't re-persist ``[G, P, L]`` tensors on every op
the way the reference's Persister re-saves one group's state
(reference quirk #6).  Durability instead pairs periodic atomic
whole-engine checkpoints with a commit-ordered write-ahead log of
acknowledged ops; acks gate on a group fsync at pump cadence.
Recovery = restore the checkpoint + re-submit WAL records through
consensus, with session dedup making replay exactly-once.

This script writes through a real TCP server process, SIGKILLs it
mid-traffic, restarts it on the same data directory, and shows every
acknowledged write intact — including appends, the op type that would
expose double-apply.
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.distributed.cluster import EngineProcessCluster


def main() -> None:
    with tempfile.TemporaryDirectory() as d:
        cluster = EngineProcessCluster(
            kind="engine_kv", groups=16, seed=23,
            data_dir=os.path.join(d, "engine"), checkpoint_every_s=2.0,
        )
        print("starting durable engine server (checkpoint every 2s + WAL)...")
        cluster.start()
        try:
            ck = cluster.clerk()
            for i in range(5):
                ck.put(f"key{i}", f"value-{i}")
            time.sleep(2.5)  # let a checkpoint cover these
            for i in range(5):
                ck.append(f"key{i}", "+wal-only")  # not yet checkpointed
            ck.close()
            print("  10 acknowledged writes (5 checkpointed, 5 WAL-only)")

            print("kill -9 ...")
            cluster.kill()
            arts = sorted(os.listdir(os.path.join(d, "engine")))
            print(f"  disk artifacts: {arts}")

            print("restarting on the same data dir (restore + WAL replay)...")
            cluster.start()
            ck = cluster.clerk()
            ok = all(
                ck.get(f"key{i}") == f"value-{i}+wal-only" for i in range(5)
            )
            assert ok, "acknowledged writes lost!"
            print("  every acknowledged write recovered, appends exactly-once")
            ck.append("key0", "+after")
            assert ck.get("key0") == "value-0+wal-only+after"
            print("  recovered server keeps serving")
            ck.close()
        finally:
            cluster.shutdown()
    print("durable engine example complete")


if __name__ == "__main__":
    main()
