"""The full sharded stack as OS processes: 3 shard-controller replicas
plus two 3-replica shard groups (9 processes total) over the native TCP
transport with disk persistence. Shard migration runs over real
sockets; a SIGKILLed replica recovers from its data directory.

The reference's shardkv only ever runs inside one simulated in-process
network (shardkv/config.go) — this is the deployment it never had.
"""

import sys, os, tempfile, time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.distributed.cluster import ShardKVProcessCluster
from multiraft_tpu.distributed.native import native_available


def main() -> None:
    if not native_available():
        print("native transport unavailable (no C++ toolchain?); skipping")
        return
    with tempfile.TemporaryDirectory() as tmp:
        cluster = ShardKVProcessCluster(tmp, gids=(100, 101), n=3)
        try:
            cluster.start_all()
            print("9 processes up: 3 controllers + 2 groups x 3 replicas")
            cluster.join(100)
            clerk = cluster.clerk()
            for i in range(10):
                clerk.put(str(i), f"v{i}")
            print("10 keys written (one per shard), all owned by group 100")

            cluster.join(101)
            conf = cluster.query()
            moved = sum(1 for g in conf.shards if g == 101)
            print(f"joined group 101: {moved} shards migrated over TCP")
            for i in range(10):
                assert clerk.get(str(i)) == f"v{i}"
            print("all keys intact after migration")

            cluster.kill((100, 0))
            clerk.append("0", "+crash")
            print(f"killed a replica; get('0') = {clerk.get('0')!r}")
            cluster.start_server(100, 0)
            print("restarted it from disk")

            cluster.leave(100)
            deadline = time.time() + 60
            while list(cluster.query().groups) != [101]:
                assert time.time() < deadline
                time.sleep(0.5)
            for i in range(10):
                expect = f"v{i}" + ("+crash" if i == 0 else "")
                assert clerk.get(str(i)) == expect
            print("group 100 drained: group 101 serves everything, data intact")
            clerk.close()
        finally:
            cluster.shutdown()
    print("OK")


if __name__ == "__main__":
    main()
