"""The batched engine served over the real network.

One OS process owns the chip: an EngineDriver with dozens-to-thousands
of Raft groups, ticking as one jitted function.  Clerk RPCs arrive over
TCP and coalesce into the device firehose; replicated KV semantics
(session dedup, linearizable ReadIndex reads) are identical to the sim
stack's — but consensus replication happens ON CHIP across the (G, P)
lanes, and the network carries client traffic only.  This is the first
step of SURVEY §2.2's sidecar story.

The sharded form (EngineShardKV) puts the full migration pipeline
behind the same front door: the second half joins a new group while
appends flow and shows values carried across the live migration.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.distributed.cluster import EngineProcessCluster


def main() -> None:
    # --- plain engine KV: concurrent clerks over sockets -------------
    cluster = EngineProcessCluster(kind="engine_kv", groups=32, seed=7)
    print("starting chip-owning engine KV server (32 groups)...")
    cluster.start()
    try:
        t0 = time.monotonic()
        n_ops = 0
        lock = threading.Lock()

        def worker(wid: int) -> None:
            nonlocal n_ops
            ck = cluster.clerk()
            try:
                for j in range(10):
                    ck.append(f"key{wid}", f".{j}")
                    with lock:
                        n_ops += 1
            finally:
                ck.close()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.monotonic() - t0
        ck = cluster.clerk()
        v = ck.get("key0")
        ck.close()
        print(
            f"4 concurrent clerks, {n_ops} appends over TCP in {dt:.2f}s "
            f"({n_ops/dt:.0f} ops/s through one socket front)"
        )
        print(f"key0 = {v!r}")
        assert v == "".join(f".{j}" for j in range(10))
    finally:
        cluster.shutdown()

    # --- sharded form: live migration under traffic -------------------
    cluster = EngineProcessCluster(
        kind="engine_shardkv", groups=4, seed=9, join_gids=[1]
    )
    print("starting sharded engine server (4 groups, gid 1 serving)...")
    cluster.start()
    try:
        ck = cluster.clerk()
        for i in range(8):
            ck.put(chr(97 + i), f"v{i}")
        print("joining gid 2 (live shard migration) while appending...")
        fut = ck.node.client_end(cluster.host, cluster.port).call(
            "EngineShardKV.admin", ("join", [2])
        )
        for i in range(8):
            ck.append(chr(97 + i), "+")
        assert ck.sched.wait(fut, 30.0).err == "OK"
        vals = [ck.get(chr(97 + i)) for i in range(8)]
        ck.close()
        print(f"after migration: {vals}")
        assert all(v == f"v{i}+" for i, v in enumerate(vals))
        print("OK: data survived the live cross-group migration")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
