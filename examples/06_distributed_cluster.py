"""Real deployment: a 3-replica KV cluster as separate OS processes
over the native TCP transport, with checksummed disk persistence —
kill a replica with SIGKILL and restart it from its data directory.

This is the runtime the reference does not have (its harness is
in-process simulation only; SURVEY §0 "no main() anywhere").
"""

import sys, os, tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.distributed.cluster import KVProcessCluster
from multiraft_tpu.distributed.native import native_available


def main() -> None:
    if not native_available():
        print("native transport unavailable (no C++ toolchain?); skipping")
        return
    with tempfile.TemporaryDirectory() as tmp:
        cluster = KVProcessCluster(3, tmp)
        try:
            cluster.start_all()
            clerk = cluster.clerk()
            clerk.put("city", "zurich")
            clerk.append("city", "+vilnius")
            print(f"3-process cluster up; get(city) = {clerk.get('city')!r}")

            cluster.kill(0)
            print("killed replica 0 (SIGKILL); majority keeps serving:")
            clerk.put("after", "crash")
            print(f"  get(after) = {clerk.get('after')!r}")

            cluster.start(0)
            print("restarted replica 0 from its data dir (disk persister)")
            assert clerk.get("city") == "zurich+vilnius"
            print("state intact after crash + restart")
            clerk.close()
        finally:
            cluster.shutdown()
    print("OK")


if __name__ == "__main__":
    main()
