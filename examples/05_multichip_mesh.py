"""Multi-chip scale-out: the engine's groups axis sharded over a
`jax.sharding.Mesh`.

Consensus traffic never crosses a group boundary, so the sharded tick
lowers with ZERO collectives — scaling is linear in devices by
construction. Here the "chips" are 8 virtual CPU devices (the same
path the driver's dryrun_multichip validates); on real hardware the
mesh is the chip/ICI topology.
"""

import sys, os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import dataclasses

import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax only exports it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiraft_tpu.engine.core import EngineConfig, empty_mailbox, init_state, tick


def main() -> None:
    devices = jax.devices()
    mesh = Mesh(devices, axis_names=("groups",))
    print(f"mesh: {len(devices)} devices along axis 'groups'")

    cfg = EngineConfig(G=64, P=3, L=32, E=4, INGEST=4)
    key = jax.random.PRNGKey(0)
    state, inbox = init_state(cfg, key), empty_mailbox(cfg)

    def pspec(x):
        sharded = getattr(x, "ndim", 0) >= 1 and x.shape and x.shape[0] == cfg.G
        return P("groups") if sharded else P()

    def spec(x):
        return NamedSharding(mesh, pspec(x))

    state = jax.tree.map(lambda x: jax.device_put(x, spec(x)), state)
    inbox = jax.tree.map(lambda x: jax.device_put(x, spec(x)), inbox)
    new_cmds = jax.device_put(
        jnp.full((cfg.G,), 2, jnp.int32), NamedSharding(mesh, P("groups"))
    )

    for i in range(120):
        state, inbox, metrics = tick(
            cfg, state, inbox, new_cmds, jax.random.fold_in(key, i)
        )
    jax.block_until_ready(state.term)

    assert state.term.sharding.spec[0] == "groups", "sharding was lost!"
    print(f"after 120 ticks: {int(metrics['leaders'])} leaders across "
          f"{cfg.G} groups, state still sharded as {state.term.sharding.spec}")
    # Proof of the scaling story: under shard_map each device runs the
    # tick on its local slice of the groups axis — the steady-state
    # fast-path conds (lax.cond on jnp.all/jnp.any predicates) evaluate
    # PER DEVICE instead of becoming cross-shard all-reduces, and the
    # global scalar metrics are dropped — so the compiled consensus
    # step contains zero collectives.
    assert cfg.G % len(devices) == 0, "G must divide over the mesh"
    local_cfg = dataclasses.replace(cfg, G=cfg.G // len(devices))

    def consensus_local(state, inbox, new_cmds, key):
        st, mb, _metrics = tick(local_cfg, state, inbox, new_cmds, key)
        return st, mb

    state_specs = jax.tree.map(pspec, state)
    inbox_specs = jax.tree.map(pspec, inbox)
    sharded_step = shard_map(
        consensus_local, mesh=mesh,
        in_specs=(state_specs, inbox_specs, P("groups"), P()),
        out_specs=(state_specs, inbox_specs),
    )
    hlo = jax.jit(sharded_step).lower(
        state, inbox, new_cmds, key
    ).compile().as_text()
    for coll in ("all-reduce", "all-gather", "collective-permute"):
        assert coll not in hlo, f"unexpected collective {coll} in sharded tick"
    print("shard_map consensus step compiles with zero collectives — "
          "per-device fast-path control flow, scaling linear in devices")


if __name__ == "__main__":
    main()
