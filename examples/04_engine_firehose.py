"""The batched TPU engine: 1,024 independent Raft groups advanced by
one jit-compiled tick function over (groups, peers) state tensors,
fed by a synthetic Start() firehose, with linearizability spot-checked
on sampled groups.

On a TPU chip the same code at G=10,000 sustains >100M commits/sec
(see bench.py); this example runs anywhere on CPU.
"""

import sys, os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import time
import numpy as np

from multiraft_tpu.engine.core import EngineConfig
from multiraft_tpu.engine.host import EngineDriver
from multiraft_tpu.engine.kv import BatchedKV, KVOp
from multiraft_tpu.porcupine.kv import OP_APPEND, OP_GET


def main() -> None:
    G = 1024
    d = EngineDriver(EngineConfig(G=G, P=3, L=64, E=8, INGEST=8), seed=1)
    print(f"ticking {G} Raft groups x 3 peers as one jitted function...")
    assert d.run_until_quiet_leaders(400)
    print(f"every group elected a leader by tick {d.tick}")

    # Firehose: saturate every group, count commits.
    t0 = time.perf_counter()
    ticks = 60
    for _ in range(ticks):
        d.start_bulk(np.full(G, 8, np.int64))
        d.step()
    dt = time.perf_counter() - t0
    print(f"{d.commits_total:,} commits in {ticks} ticks "
          f"({d.commits_total / dt:,.0f} commits/sec on CPU)")

    # The service layer on top: KV ops on a few groups, verified.
    kv = BatchedKV(d, record_groups=[0, 1])
    t = {}
    for g in (0, 1):
        kv.submit(g, KVOp(op=OP_APPEND, key="x", value=f"g{g}"))
        t[g] = kv.submit(g, KVOp(op=OP_GET, key="x"))
    for _ in range(60):
        kv.pump()
        if all(tk.done for tk in t.values()):
            break
    for g, tk in t.items():
        assert tk.done and tk.value == f"g{g}"
    kv.check_sampled_linearizability()
    print("sampled-group linearizability: OK")


if __name__ == "__main__":
    main()
