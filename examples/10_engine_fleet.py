"""An engine FLEET: several chip-owning processes, one global keyspace.

Each process runs its own batched engine (consensus on device across
its (G, P) lanes) and hosts a subset of the global replica-group space;
a replicated config — mirrored admin ops through every process's
config RSM — routes each shard to its owning process.  Shard migration
crosses the real network: the new owner pulls the shard blob with a
``pull_shard`` RPC and the old owner deletes it through its own log
(``delete_shard`` — Challenge 1 across processes).  Clerks route
key→shard→gid→process and re-route on ErrWrongGroup, the reference's
clerk loop (shardkv/client.go:68-129) where each "group" is a chip.

This is SURVEY §2.2's end state at the process level: chip↔chip work
stays on each device, node↔node traffic (client ops, shard blobs,
config admin) rides TCP.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.distributed.cluster import EngineFleetCluster


def main() -> None:
    fleet = EngineFleetCluster([[1], [2]], seed=11)
    print("starting 2 chip-owning engine processes (gid 1 | gid 2)...")
    fleet.start_all()
    try:
        print("joining gid 1 (all shards land on process 0)")
        fleet.admin("join", [1])
        clerk = fleet.clerk()
        data = {chr(97 + i): f"value-{i}" for i in range(10)}
        for k, v in data.items():
            clerk.put(k, v)
        print(f"  wrote {len(data)} keys through the fleet clerk")

        print("joining gid 2 — ~half the shards now MIGRATE to process 1")
        fleet.admin("join", [2])
        survived = sum(1 for k, v in data.items() if clerk.get(k) == v)
        print(f"  {survived}/{len(data)} keys intact across the "
              "cross-process migration")
        assert survived == len(data)

        for k in data:
            clerk.append(k, "+fleet")
        assert all(clerk.get(k) == v + "+fleet" for k, v in data.items())
        print("  appends after migration land at the new owners: OK")
        clerk.close()
    finally:
        fleet.shutdown()
    print("fleet example complete")


if __name__ == "__main__":
    main()
