"""Cross-process replica groups: one Raft group's peers on TWO
chip-owning processes, surviving a kill -9.

Everywhere else in the stack a process hosts ALL peers of its groups —
losing the process loses the whole group at once.  Here every group's
3 peer slots split 1/2 across two OS processes (engine/split.py): each
tick's boundary mailbox lanes (votes, appends, replies — plus entry
payloads and snapshot blobs) ship between the processes as slabs,
while consensus inside each chip stays zero-collective.

Two acts, demonstrated live:

1. Initial leaders are parked on process 0 (the MINORITY owner), a
   workload runs, and process 0 is SIGKILLed mid-session.  Process 1's
   two peers elect among themselves and keep serving — every
   acknowledged write intact from REPLICATION alone.
2. The cluster is DURABLE (SplitPersistence: each process fsyncs its
   owned slots' term/vote/log before each pump's slabs leave), so the
   killed process RESTARTS on its data dir and REJOINS under the same
   peer identity — the reference's Persister-carryover crash model
   (raft/config.go:113-142) at engine scale.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.distributed.cluster import SplitProcessCluster


def main() -> None:
    G = 4
    owners = {g: [0, 1, 1] for g in range(G)}  # slot 0 ↔ proc 0; 1,2 ↔ proc 1
    cluster = SplitProcessCluster(
        owners, n_procs=2, groups=G, delay_elections=[0, 300],
        data_dir=tempfile.mkdtemp(prefix="split-demo-"),
        snapshot_every_s=5.0,
    )
    print("starting 2 durable engine processes sharing every group's "
          "peers 1/2...")
    cluster.start_all()
    try:
        clerk = cluster.clerk()
        print("writing through the clerk (leaders parked on process 0)")
        for i in range(8):
            clerk.append(f"key-{i % 4}", f"[{i}]", timeout=60.0)
        print("  8 appends acknowledged")

        print("kill -9 process 0 (it hosts the LEADERS) mid-session...")
        cluster.kill(0)

        print("surviving process elects from its own quorum; serving on:")
        for i in range(8, 12):
            clerk.append(f"key-{i % 4}", f"[{i}]", timeout=60.0)
        for k in range(4):
            val = clerk.get(f"key-{k}", timeout=60.0)
            want = "".join(f"[{i}]" for i in range(12) if i % 4 == k)
            assert val == want, (k, val, want)
            print(f"  key-{k} = {val}  (every acked write intact)")
        print("act 1 OK: process loss tolerated with zero data loss")

        print("restarting process 0 from its data dir (persisted "
              "term/vote/log -> safe rejoin)...")
        cluster.start(0)
        for i in range(12, 16):
            clerk.append(f"key-{i % 4}", f"[{i}]", timeout=60.0)
        val = clerk.get("key-0", timeout=60.0)
        want = "".join(f"[{i}]" for i in range(16) if i % 4 == 0)
        assert val == want, (val, want)
        print(f"  key-0 = {val}")
        clerk.close()
        print("act 2 OK: killed process rejoined under its own identity")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
