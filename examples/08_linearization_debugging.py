"""Debugging a linearizability failure with partial linearizations.

When a history fails the porcupine check, the interesting question is
WHERE linearization got stuck.  ``check_operations_verbose`` captures,
for every operation, the longest linearizable prefix that includes it
(reference: porcupine/checker.go:219-253), and the visualizer renders
the largest such prefix as numbered linearization points — operations
it could not absorb show up red.  Click any bar in the HTML to switch
to the longest partial containing that operation.

(Reference analog: porcupine/visualization.go:89-109 +
kvraft/test_test.go:365-381, which dumps the viz on check failure.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.porcupine.checker import check_operations_verbose
from multiraft_tpu.porcupine.kv import OP_APPEND, OP_GET, OP_PUT, KvInput, KvOutput, kv_model
from multiraft_tpu.porcupine.model import Operation


def main() -> None:
    # A buggy replica served a stale read at t=[4,5]: the append at
    # t=[2,3] had already returned, but the get doesn't see it.
    h = [
        Operation(0, KvInput(op=OP_PUT, key="x", value="a"), 0.0, KvOutput(), 1.0),
        Operation(1, KvInput(op=OP_APPEND, key="x", value="b"), 2.0, KvOutput(), 3.0),
        Operation(2, KvInput(op=OP_GET, key="x"), 4.0, KvOutput(value="a"), 5.0),
        Operation(1, KvInput(op=OP_APPEND, key="x", value="c"), 6.0, KvOutput(), 7.0),
        Operation(2, KvInput(op=OP_GET, key="x"), 8.0, KvOutput(value="abc"), 9.0),
    ]
    verdict, info = check_operations_verbose(kv_model, h)
    print(f"verdict: {verdict.value}")
    largest = info.largest(0)
    print(f"longest partial linearization: {largest} "
          f"({len(largest)}/{len(h)} ops)")
    stuck = [i for i in range(len(h)) if all(i not in s for s in info.partials[0])]
    print(f"never linearized: ops {stuck} — the stale read blocks there")

    import tempfile

    from multiraft_tpu.porcupine.visualization import visualize_info

    out = os.path.join(tempfile.gettempdir(), "linearization_debug.html")
    visualize_info(kv_model, info, out, verdict, title="stale read demo")
    print(f"wrote {out} — open in a browser; red bar = the stuck read")


if __name__ == "__main__":
    main()
