"""The SHARDED stack over split replica groups: kill -9 the process
holding every leader MID-migration, and the migration still completes.

examples/12 split a plain-KV group's peers across processes; this is
the full sharded deployment (engine/split_shard.py) in the same shape:
the config RSM and every replica group have their 3 peer slots split
1/2 over two OS processes, slab exchange carrying consensus between
them.  The migration machinery — config advance, shard pulls, the
Challenge-1 delete/confirm handshake — is STATE-driven: every process
applies every group's log, so whichever process owns a leader after a
failover re-derives exactly the step a dead process never took.

The demo:

1. Two processes come up; gid 1 joins; keys are written.
2. gid 2 joins — shards start migrating 1 → 2.
3. The instant the migration is observably mid-flight, process 0
   (owning ONE slot of every group — and every leader) is SIGKILLed.
4. Process 1's quorums elect, finish the pull + GC handshake alone,
   and every acknowledged write is served back intact — no WAL, no
   disk: replication across the surviving quorum IS the durability.

Reference failure model: shardkv old-owner shutdown mid-migration
(shardkv/test_test.go:97-216) with per-server failure domains
(shardkv/config.go:204-262).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.distributed.cluster import SplitShardProcessCluster
from multiraft_tpu.services.shardkv import key2shard


def main() -> None:
    G = 3  # engine group 0 = config RSM; groups 1..2 = gids 1..2
    owners = {g: [0, 1, 1] for g in range(G)}
    cluster = SplitShardProcessCluster(
        owners, n_procs=2, groups=G, delay_elections=[0, 400],
    )
    print("starting 2 engine processes sharing the sharded stack's "
          "peer slots 1/2...")
    cluster.start_all()
    clerk = None
    try:
        clerk = cluster.clerk()
        print("join gid 1; writing 8 keys through the clerk")
        clerk.admin("join", {1: ["proc-demo"]})
        acked = {}
        keys = [chr(ord("a") + i) + "-key" for i in range(8)]
        for k in keys:
            clerk.append(k, f"[{k[0]}]")
            acked[k] = f"[{k[0]}]"
        print("  8 appends acknowledged at gid 1")

        print("join gid 2 — shards begin migrating 1 → 2...")
        clerk.admin("join", {2: ["proc-demo-2"]})
        deadline = time.monotonic() + 60.0
        mid_flight = False
        while time.monotonic() < deadline:
            st = clerk.status(0) or clerk.status(1)
            if st and st[2]:
                mid_flight = True
                break
            time.sleep(0.02)
        assert mid_flight, (
            "migration never became observable — the kill below would "
            "not demonstrate mid-migration recovery"
        )
        print("  migration observably mid-flight")

        print("kill -9 process 0 (owns ONE slot of every group — and "
              "every leader)")
        cluster.kill(0)

        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            st = clerk.status(1)
            if st and st[0] >= 2 and not st[2]:
                break
            time.sleep(0.05)
        st = clerk.status(1)
        assert st and st[0] >= 2 and not st[2], st
        print(f"  survivor finished the migration alone: config {st[0]}, "
              f"shards → {st[1]}")

        for k in keys:
            got = clerk.get(k)
            assert got == acked[k], (k, got)
        moved = next(k for k in keys if st[1][key2shard(k)] == 2)
        clerk.append(moved, "[post]")
        assert clerk.get(moved) == acked[moved] + "[post]"
        print("every acknowledged write intact; migrated shards serve "
              "fresh writes at the new owner — no WAL replay, "
              "replication was the durability")
    finally:
        if clerk is not None:
            clerk.close()
        cluster.shutdown()


if __name__ == "__main__":
    main()
