"""One 3-peer Raft group on the simulated network.

The sim stack runs in *virtual time*: a scenario spanning simulated
seconds finishes in milliseconds, deterministically, under a seed.
(Reference analog: raft/test_test.go TestInitialElection2A +
TestBasicAgree2B.)
"""

import sys, os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.harness.raft_harness import RaftHarness


def main() -> None:
    h = RaftHarness(n=3, seed=42)
    try:
        leader = h.check_one_leader()
        print(f"elected: server {leader} (virtual t={h.sched.now:.3f}s)")

        idx = h.one("hello", expected_servers=3, retry=False)
        n, cmd = h.n_committed(idx)
        print(f"agreed: {cmd!r} at index {idx} on {n}/3 servers")

        # Partition the leader away; the majority elects a new one and
        # keeps committing.
        h.disconnect(leader)
        print(f"partitioned server {leader}")
        new_leader = h.check_one_leader()
        idx = h.one("while-partitioned", expected_servers=2, retry=False)
        print(f"new leader {new_leader} committed index {idx} with 2/3 up")

        # Heal: the old leader catches up.
        h.connect(leader)
        idx = h.one("healed", expected_servers=3, retry=False)
        print(f"healed: index {idx} on all 3 (rpc total {h.rpc_total()})")
    finally:
        h.cleanup()
    print("OK")


if __name__ == "__main__":
    main()
