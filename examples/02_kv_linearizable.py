"""Linearizable KV over Raft under an unreliable network, with the
history verified by the porcupine checker and dumped as an interactive
HTML timeline.

(Reference analog: kvraft/test_test.go GenericTest + the porcupine
check at :365-381.)
"""

import sys, os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multiraft_tpu.harness.kv_harness import KVHarness
from multiraft_tpu.porcupine.checker import CheckResult, check_operations
from multiraft_tpu.porcupine.kv import OP_APPEND, OP_GET, OP_PUT, KvInput, KvOutput, kv_model
from multiraft_tpu.porcupine.model import Operation
from multiraft_tpu.porcupine.visualization import visualize


def client(cfg, history, cid, nops):
    ck = cfg.make_client()
    for j in range(nops):
        t0 = cfg.sched.now
        if j % 3 == 2:
            v = yield from ck.get("k")
            inp, out = KvInput(op=OP_GET, key="k"), KvOutput(value=v or "")
        else:
            yield from ck.append("k", f"({cid}.{j})")
            inp, out = KvInput(op=OP_APPEND, key="k", value=f"({cid}.{j})"), KvOutput(value="")
        history.append(Operation(client_id=cid, input=inp, call=t0,
                                 output=out, ret=cfg.sched.now))


def main() -> None:
    cfg = KVHarness(3, unreliable=True, seed=7)
    history: list = []
    futs = [cfg.sched.spawn(client(cfg, history, cid, nops=12)) for cid in range(4)]
    for f in futs:
        cfg.sched.run_until(f)
    print(f"ran {len(history)} ops from 4 clients over an unreliable net "
          f"(10%+10% drop, 0-26ms delay), virtual t={cfg.sched.now:.2f}s")

    res = check_operations(kv_model, history)
    assert res == CheckResult.OK, "history is not linearizable!"
    out = visualize(kv_model, history, "/tmp/kv_timeline.html",
                    verdict=res, title="02_kv_linearizable")
    print(f"linearizable: OK — timeline written to {out}")


if __name__ == "__main__":
    main()
